#!/usr/bin/env python3
"""Bench-trend regression ledger (ISSUE 12 satellite) — thin wrapper.

Reads the committed BENCH_r*.json / BENCH_SUITE.json history and prints
the samples/s-per-chip + MFU trajectory with deltas computed only
between provenance-clean (``fresh``) rows; exits 1 when the latest
fresh-vs-fresh delta regresses beyond the threshold.  The logic lives
in distributedpytorch_tpu/benchtrend.py so `main.py bench-trend` and
this script cannot drift apart (same pattern as telemetry_report.py).

Usage:
    python scripts/bench_trend.py [--dir DIR] [--threshold 0.05] [--json]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedpytorch_tpu import benchtrend  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", default=None,
                   help="directory holding BENCH_r*.json "
                        "(default: repo root)")
    p.add_argument("--threshold", type=float,
                   default=benchtrend.DEFAULT_THRESHOLD,
                   help="fractional drop in the latest fresh-vs-fresh "
                        "delta that fails the run (default 0.05)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable verdict output")
    args = p.parse_args()
    try:
        ok, text = benchtrend.run_cli(bench_dir=args.dir,
                                      threshold=args.threshold,
                                      as_json=args.json)
    except ValueError as e:
        print(f"bench-trend: {e}", file=sys.stderr)
        return 1
    print(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
