#!/usr/bin/env python3
"""A/B experiments for the cnn/b64 step optimizations (throwaway harness).

Variants:
  base            current engine (nn.max_pool -> select-and-scatter bwd)
  fastpool        custom-VJP 2x2 max pool (elementwise one-hot backward)
  pregather       epoch batches gathered in ONE take before the scan
  fastpool+pregather

Each runs the same resident cnn/b64 epoch scan, steady-state timed.

Grid mode (``--grid``): the --remat blocks x batch-size sweep on a
repeated-block model — remat trades recompute for activation memory,
so its payoff only shows against the batch sizes it unlocks; one cell
in isolation answers nothing.  Every row is a full bench.bench_ours
measurement stamped with bench.provenance_block (fresh flag, device,
git sha, timestamp) so a replayed grid can't masquerade as current.
``--scan-layers`` runs the same grid with the lax.scan block form (the
remat-inside-scan composition).  Output: one JSON document on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax

from bench import _make_corpus
from distributedpytorch_tpu import runtime, utils
from distributedpytorch_tpu.data import augment
from distributedpytorch_tpu.data.pipeline import ResidentLoader
from distributedpytorch_tpu.ops.losses import get_loss_fn


# ---- fast 2x2 max pool --------------------------------------------------

@jax.custom_vjp
def max_pool_2x2(x):
    return _pool_fwd(x)[0]


def _pool_fwd(x):
    b, h, w, c = x.shape
    xr = x.reshape(b, h // 2, 2, w // 2, 2, c)
    rowmax = jnp.max(xr, axis=4)            # (b,h2,2,w2,c)
    jidx = jnp.argmax(xr, axis=4)           # first max in row
    m = jnp.max(rowmax, axis=2)             # (b,h2,w2,c)
    iidx = jnp.argmax(rowmax, axis=2)       # first row holding the max
    jsel = jnp.where(iidx == 0, jidx[:, :, 0], jidx[:, :, 1])
    lin = (iidx * 2 + jsel).astype(jnp.int32)  # window-linear argmax
    return m, (lin, x.shape)


def _pool_bwd(res, g):
    lin, shape = res
    b, h, w, c = shape
    win = (jnp.arange(2).reshape(2, 1) * 2
           + jnp.arange(2).reshape(1, 2)).reshape(1, 1, 2, 1, 2, 1)
    dx = jnp.where(win == lin[:, :, None, :, None, :],
                   g[:, :, None, :, None, :], 0).astype(g.dtype)
    return (dx.reshape(b, h, w, c),)


max_pool_2x2.defvjp(_pool_fwd, _pool_bwd)


# even-split variant: plain reshape-max, JAX's builtin reduce_max VJP
def max_pool_2x2_even(x):
    b, h, w, c = x.shape
    xr = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return jnp.max(xr, axis=(2, 4))


# firstmask: cheap reshape-max forward; backward recomputes the FIRST-max
# mask (torch/select-and-scatter semantics) from saved (x, m) — no argmax.
@jax.custom_vjp
def max_pool_2x2_fm(x):
    b, h, w, c = x.shape
    return jnp.max(x.reshape(b, h // 2, 2, w // 2, 2, c), axis=(2, 4))


def _fm_fwd(x):
    m = max_pool_2x2_fm(x)
    return m, (x, m)


def _fm_bwd(res, g):
    x, m = res
    b, h, w, c = x.shape
    xr = x.reshape(b, h // 2, 2, w // 2, 2, c)
    mb = m[:, :, None, :, None, :]
    eq = xr == mb
    e00, e01 = eq[:, :, 0, :, 0, :], eq[:, :, 0, :, 1, :]
    e10, e11 = eq[:, :, 1, :, 0, :], eq[:, :, 1, :, 1, :]
    f00 = e00
    f01 = e01 & ~e00
    f10 = e10 & ~(e00 | e01)
    f11 = e11 & ~(e00 | e01 | e10)
    z = jnp.zeros_like(g)
    rows = jnp.stack(
        [jnp.stack([jnp.where(f00, g, z), jnp.where(f01, g, z)], axis=3),
         jnp.stack([jnp.where(f10, g, z), jnp.where(f11, g, z)], axis=3)],
        axis=2)  # (b,h2,2,w2,2,c)
    return (rows.reshape(b, h, w, c),)


max_pool_2x2_fm.defvjp(_fm_fwd, _fm_bwd)


class CNN(nn.Module):
    fast_pool: str = ""
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for width in (32, 64):
            x = nn.Conv(width, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(width, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            if self.fast_pool == "argmax":
                x = max_pool_2x2(x)
            elif self.fast_pool == "even":
                x = max_pool_2x2_even(x)
            elif self.fast_pool == "fm":
                x = max_pool_2x2_fm(x)
            else:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256, dtype=self.dtype)(x))
        x = nn.Dense(10, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def build(variant: str):
    mesh = runtime.make_mesh()
    dataset = _make_corpus(28, 1, 60000)
    loader = ResidentLoader(dataset.splits["train"], mesh, 64,
                            shuffle=True, seed=1234)
    pool = ("argmax" if "fastpool" in variant
            else "even" if "evenpool" in variant
            else "fm" if "fmpool" in variant else "")
    model = CNN(fast_pool=pool)
    tx = optax.adam(1e-3)
    loss_fn = get_loss_fn("cross_entropy")
    key = utils.root_key(1234)
    x0 = jnp.zeros((2, 28, 28, 3), jnp.bfloat16)
    params = model.init(key, x0)["params"]
    opt_state = tx.init(params)
    mean, std = dataset.mean, dataset.std

    plans = [loader.epoch_plan(e) for e in range(3)]
    idx = jnp.concatenate([p[0] for p in plans])
    valid = jnp.concatenate([p[1] for p in plans])
    n_steps = idx.shape[0]
    images_all, labels_all = loader.images, loader.labels

    def loss_of(params, im_u8, lb, v):
        aug = augment.train_transform(key, im_u8, mean, std, 28,
                                      out_dtype=jnp.bfloat16)
        out = model.apply({"params": params}, aug, train=True)
        numer, denom = loss_fn(out, lb)
        vm = v.astype(jnp.float32)
        return (jnp.sum(numer * vm) / jnp.maximum(jnp.sum(denom * vm), 1e-9))

    unroll = 1
    for part in variant.split("+"):
        if part.startswith("unroll"):
            unroll = int(part[len("unroll"):])

    if "pregather" in variant:
        def epoch(params, opt_state):
            flat = idx.reshape(-1)
            ims = jnp.take(images_all, flat, axis=0).reshape(
                n_steps, 64, 28, 28)
            lbs = jnp.take(labels_all, flat, axis=0).reshape(n_steps, 64)

            def body(carry, xs):
                params, opt_state = carry
                im, lb, v = xs
                loss, grads = jax.value_and_grad(loss_of)(params, im, lb, v)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (ims, lbs, valid),
                unroll=unroll)
            return params, opt_state, losses
    else:
        def epoch(params, opt_state):
            def body(carry, xs):
                params, opt_state = carry
                ids, v = xs
                im = jnp.take(images_all, ids, axis=0)
                lb = jnp.take(labels_all, ids, axis=0)
                loss, grads = jax.value_and_grad(loss_of)(params, im, lb, v)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (idx, valid), unroll=unroll)
            return params, opt_state, losses

    fn = jax.jit(epoch, donate_argnums=(0, 1))
    return fn, params, opt_state, n_steps


def measure(variant: str) -> float:
    fn, params, opt_state, n_steps = build(variant)
    params, opt_state, losses = fn(params, opt_state)
    jax.block_until_ready(losses)
    t0 = time.monotonic()
    params, opt_state, losses = fn(params, opt_state)
    jax.block_until_ready(losses)
    per_step = (time.monotonic() - t0) / n_steps
    print(f"{variant:22s} {per_step * 1e6:8.1f} us/step  "
          f"({64 / per_step:,.0f} samples/s)", file=sys.stderr, flush=True)
    return per_step


def run_grid(argv: list) -> None:
    import argparse

    from bench import bench_ours, provenance_block

    p = argparse.ArgumentParser(prog="exp_step_opts.py --grid")
    p.add_argument("--model", default="vit",
                   help="a REMAT_BLOCK_MODELS member (vit/densenet/"
                        "inception)")
    p.add_argument("--batches", type=int, nargs="+",
                   default=[64, 128, 256])
    p.add_argument("--steps", type=int, default=8,
                   help="steps per measured dispatch (short grid cells, "
                        "not the 12-epoch headline fusion)")
    p.add_argument("--scan-layers", action="store_true",
                   help="run the grid on the lax.scan block form "
                        "(remat-inside-scan composition)")
    args = p.parse_args(argv)

    # CPU cells run f32 (bf16 is software-emulated off-TPU and would
    # only measure the emulation); TPU cells keep the product default.
    half_precision = jax.default_backend() == "tpu"
    rows = {}
    for remat in ("none", "blocks"):
        for batch in args.batches:
            key = f"{args.model}_b{batch}_remat_{remat}" \
                + ("_scan" if args.scan_layers else "")
            try:
                row = bench_ours(
                    batch, args.steps, args.model,
                    num_train=max(batch * args.steps, 512),
                    half_precision=half_precision, remat=remat,
                    scan_layers=args.scan_layers)
            except Exception as e:
                # an OOM cell IS the grid's answer for that batch size:
                # record it as a row, keep sweeping
                rows[key] = {"error": f"{type(e).__name__}: {e}",
                             **provenance_block(fresh=True)}
                print(f"{key}: FAILED ({type(e).__name__})",
                      file=sys.stderr, flush=True)
                continue
            rows[key] = {**row, **provenance_block(fresh=True)}
            print(f"{key}: {row['samples_per_sec_per_chip']:,.0f} "
                  f"samples/s/chip, compile {row['compile_warmup_s']}s",
                  file=sys.stderr, flush=True)
    print(json.dumps({"grid": rows,
                      "config": {"model": args.model,
                                 "batches": args.batches,
                                 "steps": args.steps,
                                 "scan_layers": args.scan_layers}}),
          flush=True)


def main():
    if "--grid" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--grid"]
        run_grid(argv)
        return
    # correctness first: fast pool == nn.max_pool fwd+bwd (no ties in
    # random data; tie case checked in the real unit test later)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 16))
    ref = lambda y: jnp.sum(nn.max_pool(y, (2, 2), strides=(2, 2)) ** 2)
    fast = lambda y: jnp.sum(max_pool_2x2(y) ** 2)
    np.testing.assert_allclose(ref(x), fast(x), rtol=1e-6)
    np.testing.assert_allclose(jax.grad(ref)(x), jax.grad(fast)(x),
                               rtol=1e-6)
    fm = lambda y: jnp.sum(max_pool_2x2_fm(y) ** 2)
    np.testing.assert_allclose(ref(x), fm(x), rtol=1e-6)
    np.testing.assert_allclose(jax.grad(ref)(x), jax.grad(fm)(x),
                               rtol=1e-6)
    # tie case: identical values in one window -> first (row-major) wins
    xt = jnp.ones((1, 2, 2, 1), jnp.float32)
    gt = jax.grad(lambda y: jnp.sum(max_pool_2x2_fm(y) * 3.0))(xt)
    np.testing.assert_allclose(
        np.asarray(gt)[0, :, :, 0], [[3.0, 0.0], [0.0, 0.0]])
    print("fastpool vjp parity: OK", file=sys.stderr)

    import sys as _sys
    variants = _sys.argv[1:] or ["base", "fastpool", "pregather",
                                 "fastpool+pregather"]
    for v in variants:
        measure(v)


if __name__ == "__main__":
    main()
