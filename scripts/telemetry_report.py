#!/usr/bin/env python3
"""Aggregate a run's per-rank telemetry JSONL into a summary.

Thin wrapper over the ``telemetry`` CLI subcommand (both call
``distributedpytorch_tpu.telemetry.report``), kept as a standalone script
so report generation needs no JAX backend and works on a results
directory copied off the TPU host:

    python scripts/telemetry_report.py --rsl_path ./rsl
    python main.py telemetry --rsl_path ./rsl          # equivalent

Prints slowest spans, per-rank straggler view, data-starvation fraction,
prefetch-queue stats, samples/s/chip, MFU, and checkpoint durations.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributedpytorch_tpu import telemetry  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rsl_path", type=str, default="./rsl",
                   help="run directory holding telemetry/ (default ./rsl)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable aggregate (the same dict the "
                        "human report formats) — what gate scripts "
                        "consume instead of scraping text")
    args = p.parse_args()
    try:
        print(telemetry.json_report(args.rsl_path) if args.json
              else telemetry.report(args.rsl_path))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
