#!/usr/bin/env python3
"""Fast overlap-regression gate leg (scripts/gate.sh).

Runs a 2-epoch synthetic streaming training with every overlap feature
on (--telemetry --producer-threads --ckpt-async --aot-warmup + the
persistent compilation cache) and fails when the overlap machinery has
regressed:

  * ``data/starved_steps`` above the threshold fraction of batches —
    the background producer is no longer keeping the queue fed;
  * the telemetry report is missing the new compile gauges
    (compile/warmup_s, compile/cache_hit) or the split checkpoint spans
    (ckpt_save_blocking / ckpt_save_background).

CPU-only (the virtual test mesh) and ~1 min — runs in the gate's canary
tier, before any snapshot.
"""

import json
import os
import sys
import tempfile

MAX_STARVED_FRACTION = 0.34

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    from distributedpytorch_tpu import telemetry
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    rsl = tempfile.mkdtemp(prefix="overlap_gate_")
    cfg = Config(action="train", data_path="/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="mlp", batch_size=8,
                 nb_epochs=2, debug=True, half_precision=False,
                 telemetry=True, data_mode="stream", producer_threads=1,
                 ckpt_async=True, aot_warmup=True)
    run_train(cfg)

    with open(os.path.join(rsl, "telemetry", "rank0.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    agg = telemetry.aggregate(events)

    problems = []
    batches = agg["counters"].get("data/batches", 0)
    starved = agg["counters"].get("data/starved_steps", 0)
    if not batches:
        problems.append("no data/batches counted — streaming telemetry "
                        "is broken")
    elif starved / batches > MAX_STARVED_FRACTION:
        problems.append(
            f"producer starvation regressed: {int(starved)}/{int(batches)}"
            f" steps found the queue empty "
            f"(> {MAX_STARVED_FRACTION:.0%} threshold)")
    for gauge in ("compile/warmup_s", "compile/cache_hit"):
        if gauge not in agg["gauges"]:
            problems.append(f"missing {gauge} gauge (--aot-warmup "
                            f"telemetry broken)")
    for span in ("ckpt_save_blocking", "ckpt_save_background"):
        if span not in agg["spans"]:
            problems.append(f"missing {span} span (--ckpt-async "
                            f"telemetry broken)")

    report = telemetry.report(rsl)
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        print(report, file=sys.stderr)
        return 1
    print(f"overlap gate OK: {int(starved)}/{int(batches)} starved steps, "
          f"compile + ckpt gauges present")
    return 0


if __name__ == "__main__":
    sys.exit(main())
