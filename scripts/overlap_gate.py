#!/usr/bin/env python3
"""Fast overlap-regression gate leg (scripts/gate.sh).

Runs a 2-epoch synthetic streaming training with every overlap feature
on (--telemetry --producer-threads --ckpt-async --aot-warmup + the
persistent compilation cache) and fails when the overlap machinery has
regressed:

  * ``data/starved_steps`` above the threshold fraction of batches —
    the background producer is no longer keeping the queue fed;
  * the telemetry report is missing the new compile gauges
    (compile/warmup_s, compile/cache_hit) or the split checkpoint spans
    (ckpt_save_blocking / ckpt_save_background).

A second leg proves --device-prefetch still overlaps: the same canned
stall (an artificially slow host gather under a busy consumer) is run
with the transfer thread off and on, and the consumer's measured
blocking time (``data/wait_s`` sync vs ``data/device_wait_s``
prefetched) must drop STRICTLY — same shape as
tests/test_device_prefetch.py's unit check, but through the real
ShardedLoader + telemetry stack this gate owns.

CPU-only (the virtual test mesh) and ~1 min — runs in the gate's canary
tier, before any snapshot.
"""

import json
import os
import sys
import tempfile
import time

MAX_STARVED_FRACTION = 0.34
PREFETCH_WAIT_RATIO = 0.5  # prefetched wait must be < half the sync wait

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    from distributedpytorch_tpu import telemetry
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    rsl = tempfile.mkdtemp(prefix="overlap_gate_")
    cfg = Config(action="train", data_path="/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="mlp", batch_size=8,
                 nb_epochs=2, debug=True, half_precision=False,
                 telemetry=True, data_mode="stream", producer_threads=1,
                 ckpt_async=True, aot_warmup=True)
    run_train(cfg)

    with open(os.path.join(rsl, "telemetry", "rank0.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    agg = telemetry.aggregate(events)

    problems = []
    batches = agg["counters"].get("data/batches", 0)
    starved = agg["counters"].get("data/starved_steps", 0)
    if not batches:
        problems.append("no data/batches counted — streaming telemetry "
                        "is broken")
    elif starved / batches > MAX_STARVED_FRACTION:
        problems.append(
            f"producer starvation regressed: {int(starved)}/{int(batches)}"
            f" steps found the queue empty "
            f"(> {MAX_STARVED_FRACTION:.0%} threshold)")
    for gauge in ("compile/warmup_s", "compile/cache_hit"):
        if gauge not in agg["gauges"]:
            problems.append(f"missing {gauge} gauge (--aot-warmup "
                            f"telemetry broken)")
    for span in ("ckpt_save_blocking", "ckpt_save_background"):
        if span not in agg["spans"]:
            problems.append(f"missing {span} span (--ckpt-async "
                            f"telemetry broken)")

    problems += _device_prefetch_leg()

    report = telemetry.report(rsl)
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        print(report, file=sys.stderr)
        return 1
    print(f"overlap gate OK: {int(starved)}/{int(batches)} starved steps, "
          f"compile + ckpt gauges present, device-prefetch overlap holds")
    return 0


def _device_prefetch_leg() -> list:
    """Canned stall A/B: --device-prefetch 2 must cut the consumer's
    blocking time vs the synchronous path on an identical slow-gather /
    busy-consumer run (byte-identical batch stream either way — the
    value equality is tier-1's test_device_prefetch)."""
    from distributedpytorch_tpu import runtime, telemetry
    from distributedpytorch_tpu.data.datasets import Split
    from distributedpytorch_tpu.data.io import make_synthetic
    from distributedpytorch_tpu.data.pipeline import ShardedLoader

    delay = 0.004

    def measure(depth: int) -> float:
        tr_x, tr_y, _, _ = make_synthetic(num_train=256, num_test=8,
                                          image_size=28, channels=1,
                                          seed=0)
        loader = ShardedLoader(Split(tr_x, tr_y), runtime.make_mesh(),
                               batch_per_replica=2, shuffle=True, seed=7,
                               prefetch=2, device_prefetch=depth)
        orig = loader._host_batch

        def slow(per_rank, step):
            time.sleep(delay)  # the canned stall: slow host gather
            return orig(per_rank, step)

        loader._host_batch = slow
        rsl = tempfile.mkdtemp(prefix=f"overlap_gate_dp{depth}_")
        tel = telemetry.configure(rsl, enabled=True, rank=0)
        try:
            for _ in loader.epoch(0):
                time.sleep(delay)  # busy consumer: compute to hide under
            name = "data/device_wait_s" if depth else "data/wait_s"
            return tel.counter(name).value
        finally:
            tel.close()
            telemetry._active = telemetry.Telemetry(enabled=False)

    wait_off = measure(0)
    wait_on = measure(2)
    print(f"device-prefetch leg: consumer wait {wait_off:.3f}s sync -> "
          f"{wait_on:.3f}s with --device-prefetch 2", file=sys.stderr)
    if wait_on >= wait_off * PREFETCH_WAIT_RATIO:
        return [f"--device-prefetch overlap regressed: prefetched wait "
                f"{wait_on:.3f}s not below {PREFETCH_WAIT_RATIO:.0%} of "
                f"sync wait {wait_off:.3f}s"]
    return []


if __name__ == "__main__":
    sys.exit(main())
