#!/usr/bin/env python3
"""Fleet-simulator gate leg (scripts/gate.sh), pure CPU, no sockets.

Proves the control planes survive faults at fleet scale — the ISSUE-20
robustness contracts, each against the REAL policy code under the
deterministic simulator:

  0. calibration — fit the latency model from the committed fixture
     (tests/fixtures/sim) with scripts/extract_latency_model.py; the
     model provenance (input sha256s) must land in every report.
  A. control, N=10 — the null hypothesis: flat light traffic on an
     over-provisioned fleet produces ZERO scale actions, ZERO
     incidents, zero sheds, zero drops.  Plus determinism: the same
     seed replayed => byte-identical event log (sha256 equality).
  B. chaos, N=100 — diurnal ramp + a 6-replica stall wave + a
     30%-of-fleet preemption wave + a 300-request ioerror burst + a
     canary rollout, all at once.  Floors: zero dropped-forever
     requests, <= 2 autoscale direction changes, every preempted slot
     rejoins exactly once (no rejoin thrash), the world recovers to
     >= min_world, the rollout promotes, and the incident list is
     EXACTLY the one the fault plan designs (the ioerror burst's
     availability breach — the stall and the wave must ride through).
  C. artifact fidelity — the chaos artifacts parse through the LIVE
     pipelines: telemetry.aggregate with zero skipped records,
     tracing.reconcile with zero torn chains / violations on >= 1000
     records, goodput.report, timeline.build_timeline, and the
     incident bundles through slo.incidents_report.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/sim_gate.py``.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributedpytorch_tpu.sim import runner as sim_runner  # noqa: E402
from extract_latency_model import extract  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "sim")

_checks = []


def check(name, ok, detail=""):
    _checks.append((name, bool(ok)))
    print(f"  {'PASS' if ok else 'FAIL'}  {name}" +
          (f"  ({detail})" if detail else ""))


def assert_floors(report, floors):
    """Every floor the scenario declares, asserted against the report.
    Exact keys are exact; max_*/min-style keys are bounds."""
    name = report["scenario"]
    for key, want in sorted(floors.items()):
        if key == "scale_actions":
            got = report["scale"]["actions"]
            check(f"{name}: scale_actions == {want}", got == want,
                  f"got {got}")
        elif key == "incidents_exact":
            got = report["incidents"]
            if isinstance(want, int):
                check(f"{name}: incident count == {want}",
                      len(got) == want, f"got {got}")
            else:
                check(f"{name}: incidents == {want}", got == list(want),
                      f"got {got}")
        elif key == "dropped_forever":
            got = report["requests"]["dropped_forever"]
            check(f"{name}: dropped_forever == {want}", got == want,
                  f"got {got}")
        elif key == "max_direction_changes":
            got = report["scale"]["direction_changes"]
            check(f"{name}: direction_changes <= {want}", got <= want,
                  f"got {got}")
        elif key == "max_shed_window_s":
            got = report["shed_window_s"]
            check(f"{name}: shed_window_s <= {want}", got <= want,
                  f"got {got}")
        elif key == "max_rejoin_admits_per_replica":
            got = report["elastic"]["max_rejoin_admits_per_replica"]
            check(f"{name}: rejoin admits/replica <= {want}",
                  got <= want, f"got {got}")
        elif key == "recover_world_min":
            got = report["replicas_end"]
            check(f"{name}: world recovered >= {want}", got >= want,
                  f"got {got}")
        elif key == "rollout_outcome":
            got = report["rollout_outcome"]
            check(f"{name}: rollout {want}", got == want, f"got {got}")
        else:
            check(f"{name}: floor key {key!r} known", False)


def main():
    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="sim_gate_")

    # -- 0. calibration from the committed fixture --------------------
    print("== 0: calibrate from committed fixture")
    model, n_steps = extract(FIXTURES, batch_rows=8)
    model_path = os.path.join(tmp, "latency-model.json")
    with open(model_path, "w", encoding="utf-8") as f:
        json.dump(model, f, indent=1, sort_keys=True)
    check("fixture yields step records", n_steps >= 32,
          f"{n_steps} records")
    check("model has fitted quantities",
          set(model["quantities"]) >= {"step_s", "infer_base_s",
                                       "infer_per_row_s"})
    check("provenance pins input sha256s",
          all(i.get("sha256") for i in model["provenance"]["inputs"]))

    # -- A. control + determinism -------------------------------------
    print("== A: control scenario (null hypothesis + determinism)")
    ctl_dir = os.path.join(tmp, "control")
    ctl = sim_runner.run_scenario("control", seed=7,
                                  model_path=model_path,
                                  rsl_path=ctl_dir)
    from distributedpytorch_tpu.sim import scenario as scmod
    assert_floors(ctl, scmod.SCENARIOS["control"]["floors"])
    check("control: provenance flows into report",
          ctl["latency_model_provenance"]["source"]
          == "scripts/extract_latency_model.py")
    ctl2 = sim_runner.run_scenario("control", seed=7,
                                   model_path=model_path)
    check("control: same seed => byte-identical event log",
          ctl["event_log_sha256"] == ctl2["event_log_sha256"],
          ctl["event_log_sha256"][:12])
    ctl3 = sim_runner.run_scenario("control", seed=8,
                                   model_path=model_path)
    check("control: different seed => different log",
          ctl["event_log_sha256"] != ctl3["event_log_sha256"])

    # -- B. chaos at N=100 --------------------------------------------
    print("== B: chaos scenario (N=100, stall + wave + ioerror + canary)")
    chaos_dir = os.path.join(tmp, "chaos")
    chaos = sim_runner.run_scenario("chaos", seed=7,
                                    model_path=model_path,
                                    rsl_path=chaos_dir)
    assert_floors(chaos, scmod.SCENARIOS["chaos"]["floors"])
    r = chaos["requests"]
    check("chaos: fleet answered under fire",
          r["answered"] >= 0.9 * r["admitted"],
          f"{r['answered']}/{r['admitted']}")
    check("chaos: the wave actually happened",
          chaos["elastic"]["rejoin_admits"] == 30,
          f"{chaos['elastic']['rejoin_admits']} rejoins")
    check("chaos: ioerror burst fully consumed",
          r["failed"] == 300, f"{r['failed']} failed")

    # -- C. artifact fidelity through the LIVE pipelines --------------
    print("== C: chaos artifacts through the live pipelines")
    from distributedpytorch_tpu import (goodput, slo, telemetry,
                                        timeline, tracing)
    events = telemetry.load_events(os.path.join(chaos_dir, "telemetry"))
    agg = telemetry.aggregate(events)
    check("telemetry.aggregate: zero skipped",
          agg.get("skipped_events", 0) == 0,
          f"{len(events)} records, {len(agg['ranks'])} ranks")
    records = tracing.load_records(chaos_dir)
    problems = tracing.reconcile(records)
    check("tracing.reconcile: >= 1000 records", len(records) >= 1000,
          f"{len(records)}")
    check("tracing.reconcile: zero torn/violating records",
          not problems, problems[0] if problems else "")
    check("goodput.report renders",
          "wall-clock attribution" in goodput.report(chaos_dir))
    tl = timeline.build_timeline(chaos_dir)
    check("timeline builds over 100+ ranks",
          len(tl["ranks"]) >= 100, f"{len(tl['ranks'])} ranks")
    check("incidents_report names the designed incident",
          "availability" in slo.incidents_report(chaos_dir))

    failed = [n for n, ok in _checks if not ok]
    print(f"sim_gate: {len(_checks) - len(failed)}/{len(_checks)} "
          f"checks passed in {time.perf_counter() - t0:.1f}s")
    if failed:
        print("sim_gate: FAILED: " + "; ".join(failed))
        return 1
    print("sim_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
