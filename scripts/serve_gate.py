#!/usr/bin/env python3
"""Serving-tier gate leg (scripts/gate.sh), on CPU.

Proves ``main.py serve`` against a real checkpoint, with a real load
generator over localhost HTTP.  Three stages, all bounded:

  0. provenance — a 1-epoch synthetic mlp training run writes the
     checkpoint the server will load (same RSL dir, so the server's
     AOT bucket warmup replays the persistent XLA cache).
  A. latency + throughput floors, scraped live — a 2-bucket server
     (``--serve-buckets 1,8``) under 8 concurrent closed-loop clients.
     Pins client-side p95 latency and aggregate throughput floors (a
     serialize-everything or flush-deadline regression fails here, with
     head-room for this single-core CPU host), and scrapes the live
     exporter MID-LOAD: /metrics must carry the ``dpt_serve_*`` series
     (requests counter, latency summary quantiles), /healthz the
     tier's queue-depth extra.  SIGTERM must then drain to exit 0.
  B. saturation + shed — the same server with ``--serve-queue 8`` and
     an injected 0.25 s ``serve.infer`` stall (every micro-batch goes
     slow, so arrival far outruns service).  A 48-request burst must
     split into answered 200s and IMMEDIATE 503 sheds — counted, never
     hung, queue depth never past the bound — and the shed counter
     must land in /metrics.

Stage A also proves the ISSUE-16 observability chain end to end:
every 200 must carry an ``X-DPT-Request-Id`` header whose trace record
(trace-rank0.jsonl) reconciles — span sum == total, pre-respond spans
vs the latency histogram observation, and server total within the
latency the CLIENT measured — and a real ``main.py fleet`` collector
scraping the replica MID-load must re-export merged ``dpt_serve_*``
series equal to the per-replica scrape from the same cycle.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/serve_gate.py``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Stage A floors: deliberately loose for a single-core CPU host sharing
# client threads with the server — they pin pathologies (per-request
# compiles, a broken flush deadline, serialized handlers), not peak
# performance.
P95_MAX_MS = 2000.0
THROUGHPUT_MIN_RPS = 10.0
LOAD_CLIENTS = 8
LOAD_REQS_EACH = 8          # 64 requests total
BURST = 48                  # stage B concurrent one-shot clients
STALL_PLAN = "serve.infer:stall:0:1000000:0.25"

SAMPLE = [[(r * 28 + c) % 256 for c in range(28)] for r in range(28)]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env() -> dict:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _post(port: int, timeout: float = 35.0):
    """One /predict round trip -> (status, body dict, client seconds,
    X-DPT-Request-Id header or None)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": SAMPLE}).encode())
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return (r.status, json.loads(r.read()),
                    time.perf_counter() - t0,
                    r.headers.get("X-DPT-Request-Id"))
    except urllib.error.HTTPError as e:
        return (e.code, json.loads(e.read()), time.perf_counter() - t0,
                e.headers.get("X-DPT-Request-Id"))


def _scrape(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode("utf-8")


def _wait_live(port: int, proc, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server exited rc={proc.returncode} before serving")
        try:
            if json.loads(_scrape(port, "/livez")).get("ok"):
                return
        except (OSError, ValueError):
            time.sleep(0.2)
    raise RuntimeError(f"server not live on :{port} within {timeout_s}s")


def _launch_server(rsl: str, ckpt: str, port: int, metrics_port: int,
                   queue: int, extra=(), tag: str = "serve"):
    cmd = [sys.executable, "main.py", "serve", "-d", "/nodata",
           "--dataset", "synthetic", "--model", "mlp", "-f", ckpt,
           "--rsl_path", rsl, "--serve-port", str(port),
           "--serve-buckets", "1,8", "--serve-max-latency-ms", "5",
           "--serve-queue", str(queue),
           "--metrics-port", str(metrics_port), *extra]
    log = open(os.path.join(rsl, f"{tag}.log"), "w")
    proc = subprocess.Popen(cmd, cwd=REPO, env=_env(), stdout=log,
                            stderr=subprocess.STDOUT)
    return proc, log


def _stop_server(proc, log, problems, tag: str) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        problems.append(f"{tag}: server hung on SIGTERM — drain broke")
        rc = None
    if rc not in (0, None):
        problems.append(f"{tag}: SIGTERM drain exited rc={rc}, "
                        f"expected 0 (see {log.name})")
    log.close()


def main() -> int:
    problems = []
    work = tempfile.mkdtemp(prefix="serve_gate_")
    rsl = os.path.join(work, "rsl")

    # -- stage 0: train the checkpoint the server will load -----------
    t0 = time.perf_counter()
    train = subprocess.run(
        [sys.executable, "main.py", "train", "-d", "/nodata",
         "--dataset", "synthetic", "--model", "mlp", "-b", "8",
         "-e", "1", "--rsl_path", rsl],
        cwd=REPO, env=_env(), capture_output=True, text=True)
    if train.returncode != 0:
        print(f"PROBLEM: checkpoint-provenance training run failed "
              f"rc={train.returncode}:\n{train.stdout[-800:]}\n"
              f"{train.stderr[-800:]}", file=sys.stderr)
        return 1
    ckpt = os.path.join(rsl, "bestmodel-synthetic-mlp.ckpt")
    print(f"serve gate 0: checkpoint trained in "
          f"{time.perf_counter() - t0:.1f}s")

    # -- stage A: floors + live scrape under concurrent load ----------
    port, mport = _free_port(), _free_port()
    fport = _free_port()
    proc, log = _launch_server(rsl, ckpt, port, mport, queue=64,
                               tag="serve_a")
    # the fleet collector rides along, scraping the replica's exporter
    # on a tight interval so a merged cycle exists mid-load
    fleet_log = open(os.path.join(work, "fleet_a.log"), "w")
    fleet_proc = subprocess.Popen(
        [sys.executable, "main.py", "fleet", "--rsl_path", rsl,
         "--metrics-port", str(mport), "--ranks", "1",
         "--fleet-port", str(fport), "--interval", "0.2",
         "--stale-after", "5"],
        cwd=REPO, env=_env(), stdout=fleet_log,
        stderr=subprocess.STDOUT)
    try:
        _wait_live(port, proc)
        status, body, _, rid = _post(port)  # functional round trip first
        if status != 200 or not (0.0 < body.get("confidence", 0) <= 1.0):
            problems.append(f"A: warm request failed: {status} {body}")
        if not (rid or "").startswith("r0-"):
            problems.append(f"A: 200 answer missing X-DPT-Request-Id "
                            f"(got {rid!r})")
        # a fleet cycle must have seen the replica before load starts
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                if json.loads(_scrape(fport, "/fleet")).get("alive"):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        else:
            problems.append("A: fleet collector never reported the "
                            "replica alive")

        results, mid_metrics, mid_health = [], [None], [None]
        mid_fleet, mid_fleet_prom = [None], [None]
        lock = threading.Lock()

        def client():
            for _ in range(LOAD_REQS_EACH):
                out = _post(port)
                with lock:
                    results.append(out)

        def scraper():
            # mid-load by construction: fires while clients are running
            time.sleep(0.3)
            try:
                mid_metrics[0] = _scrape(mport, "/metrics")
                mid_health[0] = json.loads(_scrape(mport, "/healthz"))
            except (OSError, ValueError) as e:
                problems.append(f"A: mid-load scrape failed: {e}")
            try:
                mid_fleet[0] = json.loads(_scrape(fport, "/fleet"))
                mid_fleet_prom[0] = _scrape(fport, "/metrics")
            except (OSError, ValueError) as e:
                problems.append(f"A: mid-load fleet scrape failed: {e}")

        threads = [threading.Thread(target=client)
                   for _ in range(LOAD_CLIENTS)]
        threads.append(threading.Thread(target=scraper))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        elapsed = time.perf_counter() - t0

        total = LOAD_CLIENTS * LOAD_REQS_EACH
        if len(results) != total:
            problems.append(f"A: {total - len(results)} of {total} "
                            f"requests never returned — hung clients")
        bad = [(s, b) for s, b, _, _ in results if s != 200]
        if bad:
            problems.append(f"A: {len(bad)} non-200 answers under "
                            f"in-bounds load, first: {bad[0]}")
        # every 200 carries a unique request id the server minted
        rids = [r for s, _, _, r in results if s == 200]
        if any(not (r or "").startswith("r0-") for r in rids):
            n = sum(1 for r in rids if not (r or "").startswith("r0-"))
            problems.append(f"A: {n} of {len(rids)} 200s missing a "
                            f"well-formed X-DPT-Request-Id header")
        elif len(set(rids)) != len(rids):
            problems.append(f"A: request ids not unique: "
                            f"{len(rids) - len(set(rids))} duplicates")
        if results:
            lats = sorted(dt * 1000.0 for _, _, dt, _ in results)
            p50 = lats[len(lats) // 2]
            p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))]
            rps = len(results) / elapsed
            if p95 > P95_MAX_MS:
                problems.append(f"A: client p95 {p95:.0f}ms over the "
                                f"{P95_MAX_MS:.0f}ms floor")
            if rps < THROUGHPUT_MIN_RPS:
                problems.append(f"A: throughput {rps:.1f} req/s under "
                                f"the {THROUGHPUT_MIN_RPS} req/s floor")
            print(f"serve gate A: {len(results)} reqs in {elapsed:.2f}s "
                  f"({rps:.0f} req/s), p50 {p50:.0f}ms p95 {p95:.0f}ms")

        body = mid_metrics[0] or ""
        for needle in ("dpt_serve_requests_total",
                       'dpt_serve_request_latency_ms{quantile="0.95"}',
                       "dpt_serve_batches_total", "dpt_up 1"):
            if needle not in body:
                problems.append(f"A: mid-load /metrics missing "
                                f"{needle!r}")
        health = mid_health[0] or {}
        if "serve" not in health or "queue_depth" not in \
                health.get("serve", {}):
            problems.append(f"A: /healthz missing the serve extra "
                            f"(queue depth): {health}")

        # fleet mid-load: merged series == sum of the per-replica
        # scrapes from the SAME collector cycle (one replica here, so
        # equality is exact — any drift means the merge mangled it)
        doc = mid_fleet[0]
        if not doc:
            problems.append("A: no mid-load /fleet document")
        else:
            if doc.get("alive") != [0]:
                problems.append(f"A: fleet alive {doc.get('alive')}, "
                                f"expected [0]")
            for series in ("dpt_serve_requests_total",
                           "dpt_serve_batches_total"):
                merged = doc.get("counters", {}).get(series)
                per = sum(t["counters"].get(series, 0.0)
                          for t in doc.get("targets", {}).values())
                if merged is None or merged != per:
                    problems.append(
                        f"A: fleet merged {series}={merged} != sum of "
                        f"per-replica scrapes {per} (same cycle)")
                elif series == "dpt_serve_requests_total" and \
                        merged < 1.0:
                    problems.append(f"A: fleet merged {series} is "
                                    f"{merged} mid-load — collector "
                                    f"scraped nothing")
            prom = mid_fleet_prom[0] or ""
            if "dpt_serve_requests_total" not in prom or \
                    not prom.endswith("dpt_up 1\n"):
                problems.append("A: fleet /metrics re-export missing "
                                "dpt_serve_* or dpt_up trailer")
    finally:
        fleet_proc.terminate()
        try:
            fleet_proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            fleet_proc.kill()
            fleet_proc.wait()
        fleet_log.close()
        _stop_server(proc, log, problems, "A")

    # trace reconciliation: snapshot trace-rank0.jsonl NOW, before
    # stage B's fresh server appends to it with a restarted sequence
    from distributedpytorch_tpu import tracing
    records = [r for r in tracing.load_records(rsl)
               if r.get("rank") == 0]
    if len(records) < len(results):
        problems.append(f"A: only {len(records)} trace records for "
                        f"{len(results)} requests")
    torn = tracing.reconcile(records)
    if torn:
        problems.append(f"A: {len(torn)} trace record(s) fail "
                        f"reconciliation, first: {torn[0]}")
    by_id = {r["id"]: r for r in records}
    missing = [rid for _, _, _, rid in
               [x for x in results if x[0] == 200]
               if rid not in by_id]
    if missing:
        problems.append(f"A: {len(missing)} answered request id(s) "
                        f"have no trace record, first: {missing[0]}")
    # the server's span total can never exceed what the CLIENT timed
    # (client adds connect + transfer); allow scheduling slack on this
    # shared single-core host
    over = [(rid, by_id[rid]["total_s"], dt)
            for s, _, dt, rid in results
            if s == 200 and rid in by_id
            and by_id[rid]["total_s"] > dt + 0.25]
    if over:
        rid, srv, cli = over[0]
        problems.append(f"A: {len(over)} trace total(s) exceed the "
                        f"client-measured latency, first: {rid} "
                        f"server {srv * 1000:.0f}ms vs client "
                        f"{cli * 1000:.0f}ms")
    print(f"serve gate A: {len(records)} trace records reconciled "
          f"against client latencies")

    # -- stage B: saturation — shed counted, never hung ---------------
    port, mport = _free_port(), _free_port()
    proc, log = _launch_server(
        rsl, ckpt, port, mport, queue=8,
        extra=("--fault-plan", STALL_PLAN), tag="serve_b")
    try:
        _wait_live(port, proc)
        results = []
        lock = threading.Lock()

        def one_shot():
            out = _post(port)
            with lock:
                results.append(out)

        threads = [threading.Thread(target=one_shot)
                   for _ in range(BURST)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        elapsed = time.perf_counter() - t0

        if len(results) != BURST:
            problems.append(f"B: {BURST - len(results)} of {BURST} "
                            f"burst requests never returned — a full "
                            f"queue HUNG clients instead of shedding")
        answered = [r for r in results if r[0] == 200]
        shed = [r for r in results if r[0] == 503]
        other = [r for r in results if r[0] not in (200, 503)]
        if other:
            problems.append(f"B: unexpected status under saturation, "
                            f"first: {other[0][:2]}")
        if not shed:
            problems.append(f"B: no 503 sheds out of {BURST} burst "
                            f"requests against a queue of 8 with a "
                            f"0.25s/batch stall — backpressure is not "
                            f"answering")
        if not answered:
            problems.append("B: nothing answered under saturation — "
                            "shedding everything is an outage, not "
                            "backpressure")
        for _, b, _, _ in shed:
            if b.get("queue_depth", 0) > 8:
                problems.append(f"B: shed response reports queue depth "
                                f"{b['queue_depth']} past the bound 8 "
                                f"— the queue grew")
                break
        # shed answers must be immediate, not timed out: the slowest
        # shed stays far under the 0.25s/batch service time backlog
        slow_shed = [dt for s, _, dt, _ in results
                     if s == 503 and dt > 5.0]
        if slow_shed:
            problems.append(f"B: {len(slow_shed)} shed answer(s) took "
                            f">5s — 503s must be immediate")
        try:
            metrics = _scrape(mport, "/metrics")
            if "dpt_serve_shed_total" not in metrics:
                problems.append("B: dpt_serve_shed_total missing from "
                                "/metrics after sheds")
        except OSError as e:
            problems.append(f"B: post-burst /metrics scrape failed: {e}")
        print(f"serve gate B: burst {BURST} -> {len(answered)} "
              f"answered, {len(shed)} shed in {elapsed:.2f}s")
    finally:
        _stop_server(proc, log, problems, "B")

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("serve gate OK: floors held under load, live dpt_serve_* "
          "metrics scraped mid-run, traces reconciled + fleet merge "
          "matched per-replica scrapes, saturation shed with 503s "
          "(counted, never hung), SIGTERM drained clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
