#!/usr/bin/env python
"""Fit the simulator's latency model from real run artifacts.

Reads flightrec dumps (``flightrec-rank*.json``) and, when present, the
goodput ledger from a run directory, fits the quantile sketches
sim/latency.py samples from, and writes a model JSON stamped with full
provenance (input files, sha256s, record counts, the batch_rows
assumption) — so every simulated result names its calibration source.

Quantity mapping, stated once:

  step_s          <- each step record's ``step_s`` (training realism),
  infer_base_s    <- ``dispatch_s`` — the accelerator dispatch slice is
                     the fixed cost of one simulated batch dispatch,
  infer_per_row_s <- (step_s - dispatch_s) / batch_rows — the host-side
                     per-step tail amortized over the rows of one batch
                     (the marginal row cost the planner's padding pays).

``respond_s`` is intentionally NOT fitted: flightrec doesn't observe a
serving write-back, and inventing one here would be calibration
theater.  The sampler falls back to the built-in default for any
quantity a model file omits.

Usage:
  python scripts/extract_latency_model.py RUN_DIR [-o MODEL.json]
                                          [--batch-rows N]
"""

import argparse
import glob
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributedpytorch_tpu.sim import latency as latmod  # noqa: E402


def _quantiles(values):
    """Empirical quantiles at the sketch's pinned points (sorted-array
    interpolation — scipy-free on purpose)."""
    vs = sorted(values)
    out = {}
    for key, q in latmod.QUANTILES:
        pos = q * (len(vs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vs) - 1)
        out[key] = round(vs[lo] + (vs[hi] - vs[lo]) * (pos - lo), 6)
    return out


def extract(run_dir, batch_rows=8):
    """Returns (model_doc, n_steps).  ValueError when the directory has
    nothing to fit from."""
    paths = sorted(glob.glob(os.path.join(run_dir, "flightrec-rank*.json")))
    if not paths:
        raise ValueError(
            f"no flightrec-rank*.json under {run_dir!r} — the model is "
            f"fitted from flight-recorder step records")
    if batch_rows < 1:
        raise ValueError(f"--batch-rows must be >= 1 (got {batch_rows})")
    step_s, base_s, per_row_s = [], [], []
    inputs = []
    for path in paths:
        with open(path, "rb") as f:
            blob = f.read()
        doc = json.loads(blob)
        n = 0
        for rec in doc.get("records", []):
            if rec.get("kind") != "step":
                continue
            s = rec.get("step_s")
            if not isinstance(s, (int, float)) or s <= 0:
                continue
            n += 1
            step_s.append(float(s))
            d = rec.get("dispatch_s")
            if isinstance(d, (int, float)) and 0 < d <= s:
                base_s.append(float(d))
                per_row_s.append((float(s) - float(d)) / batch_rows)
        inputs.append({"path": os.path.basename(path),
                       "sha256": hashlib.sha256(blob).hexdigest(),
                       "step_records": n})
    if not step_s:
        raise ValueError(
            f"flightrec dumps under {run_dir!r} hold no usable step "
            f"records (need kind='step' with step_s > 0)")
    quantities = {"step_s": _quantiles(step_s)}
    if base_s:
        quantities["infer_base_s"] = _quantiles(base_s)
        quantities["infer_per_row_s"] = _quantiles(per_row_s)
    provenance = {"source": "scripts/extract_latency_model.py",
                  "run_dir": os.path.basename(os.path.abspath(run_dir)),
                  "batch_rows": int(batch_rows), "inputs": inputs}
    gp = os.path.join(run_dir, "goodput.json")
    if os.path.exists(gp):
        with open(gp, "rb") as f:
            gblob = f.read()
        ledger = json.loads(gblob)
        provenance["goodput"] = {
            "path": "goodput.json",
            "sha256": hashlib.sha256(gblob).hexdigest(),
            "wall_s": ledger.get("wall_s"),
            "compute_frac": (
                round(ledger["categories"].get("compute", 0.0)
                      / ledger["wall_s"], 6)
                if ledger.get("wall_s") else None)}
    model = {"version": 1, "provenance": provenance,
             "quantities": quantities}
    latmod.validate_model(model, where="extracted model")
    return model, len(step_s)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="directory holding "
                                    "flightrec-rank*.json (+ goodput.json)")
    ap.add_argument("-o", "--out", default="latency-model.json")
    ap.add_argument("--batch-rows", type=int, default=8,
                    help="rows per batch when amortizing the per-step "
                         "tail into a per-row cost (default 8)")
    args = ap.parse_args(argv)
    try:
        model, n = extract(args.run_dir, batch_rows=args.batch_rows)
    except ValueError as e:
        print(f"extract_latency_model: {e}", file=sys.stderr)
        return 1
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(model, f, indent=1, sort_keys=True)
        f.write("\n")
    qs = {name: q["p50"] for name, q in model["quantities"].items()}
    print(f"extract_latency_model: fitted {len(model['quantities'])} "
          f"quantities from {n} step records -> {args.out} "
          f"(p50s: {qs})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
