#!/usr/bin/env python3
"""Front-door / autoscale / rollout gate leg (scripts/gate.sh), on CPU.

Proves ``main.py frontdoor`` end to end over real ``main.py serve``
replicas — the ISSUE-19 robustness contracts, each against live HTTP:

  0. provenance — a 2-epoch synthetic mlp run leaves a rolling
     checkpoint lineage: the oldest verified ledger entry is the
     fleet's STABLE, the newest the head the watcher will canary.
  A. canary auto-rollback, zero client-visible 500s — two replicas on
     the stable checkpoint; replica 0 fault-injected so every infer
     500s.  The front door (``--rollout``) canaries the ledger head
     onto replica 0, the judge sees the canary error ratio dwarf
     stable's, rolls back, restores the stable checkpoint onto the
     replica and blacklists the sha — while closed-loop clients see
     nothing but 200s (retry-once absorbs every canary 500).
  B. kill + --elastic-join repair while answering — a real 2-process
     elastic serve world (rank 1 joined via ``main.py serve --elastic
     --elastic-join``).  SIGKILL rank 1 mid-load: the front door
     ejects it, the embedded collector ages it out, the autoscale
     controller repairs world < min_world by launching the SAME
     join command, and the joiner re-enters at rank 1 (its old
     port) — clients keep seeing 200s through the whole window.
  C. clean control — two replicas already serving the ledger head:
     zero rollbacks, zero promotions, zero scale events, all 200s,
     and every trace record stamped with the served lineage sha.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python
scripts/rollout_gate.py``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributedpytorch_tpu.serving.rollout import (  # noqa: E402
    LINEAGE_FILE, newest_lineage_entry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAIN = os.path.join(REPO, "main.py")

SAMPLE = [[(r * 28 + c) % 256 for c in range(28)] for r in range(28)]
CANARY_FAULT = "serve.infer:ioerror:0:1000000"
LIVE_WAIT_S = 150.0
JOIN_WAIT_S = 240.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _free_block(n: int) -> int:
    """A base port with ``n`` consecutive free ports above it (the
    front door maps replica slot i to base + i)."""
    for _ in range(64):
        base = _free_port()
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no block of {n} consecutive free ports")


def _env() -> dict:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _scrape(port: int, path: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read().decode("utf-8")


def _post(port: int, timeout: float = 150.0):
    """One /predict round trip through the front door -> (status,
    body dict).  Transport failures return (-1, {"error": repr})."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": SAMPLE}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except ValueError:
            body = {}
        return e.code, body
    except OSError as e:
        return -1, {"error": repr(e)}


def _status(port: int) -> dict:
    """The front door's own /healthz (status_doc)."""
    return json.loads(_scrape(port, "/healthz"))


def _wait_live(port: int, proc, timeout_s: float, what: str,
               log: str = "") -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return False
        try:
            if json.loads(_scrape(port, "/livez")).get("ok"):
                return True
        except (OSError, ValueError):
            time.sleep(0.3)
    return False


def _wait_status(port: int, pred, timeout_s: float):
    """Poll the front door's status doc until ``pred(doc)`` or
    timeout; returns the last doc (or None if never reachable)."""
    doc = None
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            doc = _status(port)
            if pred(doc):
                return doc
        except (OSError, ValueError):
            pass
        time.sleep(0.3)
    return doc


def _events(rsl: str, rank: int):
    path = os.path.join(rsl, "telemetry", f"rank{rank}.jsonl")
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    except OSError:
        pass
    return out


def _tail(path: str, n: int = 30) -> str:
    try:
        with open(path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError:
        return f"<no log at {path}>"


def _serve_cmd(rsl: str, ckpt: str, port: int, cache: str,
               metrics_port: int = 0, extra=()):
    cmd = [sys.executable, MAIN, "serve", "-d", "/nodata",
           "--dataset", "synthetic", "--model", "mlp", "-f", ckpt,
           "--rsl_path", rsl, "--serve-port", str(port),
           "--serve-buckets", "1,8", "--serve-max-latency-ms", "5",
           "--serve-queue", "64",
           "--compilation-cache-dir", cache]
    if metrics_port:
        cmd += ["--metrics-port", str(metrics_port)]
    return cmd + list(extra)


def _launch(cmd, log_path: str):
    log = open(log_path, "wb")
    proc = subprocess.Popen(cmd, cwd=REPO, env=_env(), stdout=log,
                            stderr=subprocess.STDOUT)
    return proc, log


def _stop(proc, log, problems, tag: str, timeout_s: float = 90.0):
    """SIGTERM -> clean rc 0 (drain / coordinated preempt)."""
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        problems.append(f"{tag}: hung on SIGTERM — drain broke "
                        f"(killed)\n{_tail(log.name)}")
        rc = None
    if rc not in (0, None):
        problems.append(f"{tag}: SIGTERM exit rc={rc}, expected 0"
                        f"\n{_tail(log.name)}")
    log.close()


class _Load:
    """Closed-loop client threads against the front door; every
    (status, body) is recorded for the zero-5xx assertions."""

    def __init__(self, port: int, clients: int = 2,
                 pause_s: float = 0.02):
        self.port = port
        self.pause_s = pause_s
        self.results = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(clients)]

    def _run(self):
        while not self._stop.is_set():
            out = _post(self.port)
            with self._lock:
                self.results.append(out)
            self._stop.wait(self.pause_s)

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=180)
        return self.results

    def bad(self):
        with self._lock:
            return [(s, b) for s, b in self.results if s != 200]


# -- stage A: canary rollback, zero client 500s ------------------------

def stage_canary_rollback(work, rsl, cache, stable, stable_sha, head):
    problems = []
    base = _free_block(2)
    fdp = _free_port()
    fd_rsl = os.path.join(work, "fd_a")
    reps = []
    for i, extra in ((0, ("--fault-plan", CANARY_FAULT)), (1, ())):
        rrsl = os.path.join(work, f"rsl_a{i}")
        reps.append(_launch(
            _serve_cmd(rrsl, stable, base + i, cache, extra=extra),
            os.path.join(work, f"serve_a{i}.log")))
    fd = None
    load = None
    try:
        for i, (proc, log) in enumerate(reps):
            if not _wait_live(base + i, proc, LIVE_WAIT_S,
                              f"replica {i}"):
                return [f"A: replica {i} never went live on "
                        f":{base + i}\n{_tail(log.name)}"]
        fd = _launch(
            [sys.executable, MAIN, "frontdoor", "--rsl_path", fd_rsl,
             "--port", str(fdp), "--ranks", "2",
             "--serve-port", str(base), "--interval", "0.3",
             "--upstream-timeout", "30", "--rollout",
             "--watch-dir", rsl, "--canary-fraction", "0.34",
             "--canary-hold", "60", "--canary-min-requests", "6",
             "--canary-max-error", "0.2"],
            os.path.join(work, "frontdoor_a.log"))
        doc = _wait_status(
            fdp, lambda d: all(d["upstreams"][str(i)]["alive"]
                               for i in (0, 1)), 60.0)
        if not doc or not all(doc["upstreams"][str(i)]["alive"]
                              for i in (0, 1)):
            return [f"A: front door never probed both replicas alive: "
                    f"{doc}\n{_tail(fd[1].name)}"]
        load = _Load(fdp).start()
        doc = _wait_status(
            fdp, lambda d: d["rollout"]["rollbacks"] >= 1, 120.0)
        if not doc or doc["rollout"]["rollbacks"] < 1:
            problems.append(f"A: no rollback within 120s — rollout "
                            f"doc {doc and doc['rollout']}"
                            f"\n{_tail(fd[1].name)}")
        else:
            print(f"rollout gate A: canary rolled back "
                  f"(doc: {doc['rollout']})")
            if doc["rollout"]["phase"] != "stable" \
                    or doc["rollout"]["canary_ids"]:
                problems.append(f"A: post-rollback rollout state not "
                                f"stable: {doc['rollout']}")
            # the rejected sha must never canary again
            time.sleep(2.0)
            doc2 = _status(fdp)
            if doc2["rollout"]["rollbacks"] != 1 \
                    or doc2["rollout"]["phase"] != "stable":
                problems.append(f"A: rejected sha canaried again: "
                                f"{doc2['rollout']}")
            # replica 0 restored onto the stable checkpoint
            doc3 = _wait_status(
                fdp, lambda d: (d["upstreams"]["0"]["lineage"] or {})
                .get("sha256") == stable_sha, 30.0)
            got = ((doc3 or {}).get("upstreams", {}).get("0", {})
                   .get("lineage") or {}).get("sha256")
            if got != stable_sha:
                problems.append(f"A: replica 0 lineage after rollback "
                                f"is {got!r}, expected the stable sha "
                                f"{stable_sha[:12]}")
        results = load.stop()
        load = None
        fives = [(s, b) for s, b in results if s >= 500 or s < 0]
        if fives:
            problems.append(f"A: {len(fives)} client-visible failures "
                            f"through the canary+rollback window, "
                            f"first: {fives[0]} — retry-once did not "
                            f"absorb the canary 500s")
        if not any(s == 200 for s, _ in results):
            problems.append("A: no client 200s at all — nothing was "
                            "actually served")
        doc = _status(fdp)
        if doc["retries"] < 1:
            problems.append(f"A: retries={doc['retries']} — the faulted "
                            f"canary never exercised retry-once")
        names = [e.get("name") for e in _events(fd_rsl, 90)]
        for needed in ("frontdoor_start", "rollout/canary_start",
                       "rollout/rollback"):
            if needed not in names:
                problems.append(f"A: telemetry event {needed!r} missing "
                                f"from the front door's JSONL ({names})")
        print(f"rollout gate A: {len(results)} client requests, "
              f"{len(fives)} failures, retries={doc['retries']}")
    finally:
        if load is not None:
            load.stop()
        if fd is not None:
            _stop(fd[0], fd[1], problems, "A: frontdoor", 30.0)
        for i, (proc, log) in enumerate(reps):
            _stop(proc, log, problems, f"A: replica {i}")
    return problems


# -- stage B: SIGKILL + --elastic-join repair --------------------------

def stage_kill_and_join(work, rsl, cache, head):
    problems = []
    base = _free_block(3)
    mb = _free_block(3)
    fdp = _free_port()
    rsl_b = os.path.join(work, "rsl_b")     # shared by world members
    fd_rsl = os.path.join(work, "fd_b")
    elastic = ("--elastic", "--health-timeout", "5",
               "--max-reconfigures", "6",
               "--serve-request-timeout", "120")
    rank0 = _launch(
        _serve_cmd(rsl_b, head["path"], base, cache,
                   metrics_port=mb, extra=elastic),
        os.path.join(work, "serve_b0.log"))
    join_cmd = _serve_cmd(rsl_b, head["path"], base, cache,
                          metrics_port=mb,
                          extra=elastic + ("--elastic-join",))
    fd = None
    joiner = None
    load = None
    try:
        if not _wait_live(base, rank0[0], LIVE_WAIT_S, "rank 0"):
            return [f"B: rank 0 never went live on :{base}"
                    f"\n{_tail(rank0[1].name)}"]
        # grow the world to 2 through the SAME join command the
        # controller will later use for the repair
        joiner = _launch(join_cmd, os.path.join(work, "serve_b1.log"))
        if not _wait_live(base + 1, joiner[0], JOIN_WAIT_S, "joiner"):
            return [f"B: elastic joiner never went live on "
                    f":{base + 1}\n{_tail(joiner[1].name)}"]
        print("rollout gate B: 2-process elastic serve world up "
              "(rank 1 via --elastic-join)")
        fd = _launch(
            [sys.executable, MAIN, "frontdoor", "--rsl_path", fd_rsl,
             "--port", str(fdp), "--ranks", "2",
             "--serve-port", str(base), "--metrics-port", str(mb),
             "--interval", "0.5", "--upstream-timeout", "60",
             "--stale-after", "3", "--autoscale",
             "--min-world", "2", "--max-world", "2",
             "--queue-high", "999999", "--queue-low", "0",
             "--up-hold", "2", "--down-hold", "3600",
             "--cooldown", "120",
             "--launch-cmd", " ".join(join_cmd)],
            os.path.join(work, "frontdoor_b.log"))
        doc = _wait_status(
            fdp, lambda d: all(d["upstreams"][str(i)]["alive"]
                               for i in (0, 1)), 60.0)
        if not doc or not all(doc["upstreams"][str(i)]["alive"]
                              for i in (0, 1)):
            return [f"B: front door never probed both replicas alive: "
                    f"{doc}\n{_tail(fd[1].name)}"]
        load = _Load(fdp).start()
        doc = _wait_status(
            fdp, lambda d: all(d["upstreams"][str(i)]["requests"] > 0
                               for i in (0, 1)), 30.0)
        if not doc or not all(doc["upstreams"][str(i)]["requests"] > 0
                              for i in (0, 1)):
            problems.append(f"B: load never reached both replicas "
                            f"before the kill: {doc}")
        served_before = doc["upstreams"]["1"]["requests"] if doc else 0
        joiner[0].kill()    # SIGKILL: no drain, no goodbye
        print("rollout gate B: rank 1 SIGKILLed mid-load")
        doc = _wait_status(fdp, lambda d: d["scale_events"] >= 1, 120.0)
        if not doc or doc["scale_events"] < 1:
            problems.append(
                f"B: controller never repaired the world within 120s "
                f"(scale_events={doc and doc['scale_events']})"
                f"\n{_tail(fd[1].name)}\n--- join-1.log ---\n"
                f"{_tail(os.path.join(fd_rsl, 'join-1.log'))}")
        else:
            doc = _wait_status(
                fdp, lambda d: (d["upstreams"]["1"]["alive"]
                                and not d["upstreams"]["1"]["ejected"]
                                and d["upstreams"]["1"]["requests"]
                                > served_before), JOIN_WAIT_S)
            up1 = (doc or {}).get("upstreams", {}).get("1", {})
            if not up1.get("alive") or up1.get("ejected") \
                    or up1.get("requests", 0) <= served_before:
                problems.append(
                    f"B: replacement joiner never took traffic on slot "
                    f"1 (snapshot {up1})\n{_tail(fd[1].name)}\n"
                    f"--- join-1.log ---\n"
                    f"{_tail(os.path.join(fd_rsl, 'join-1.log'))}")
            else:
                print(f"rollout gate B: slot 1 repaired and serving "
                      f"again ({up1['requests']} requests, "
                      f"{served_before} before the kill)")
        results = load.stop()
        load = None
        fives = [(s, b) for s, b in results if s >= 500 or s < 0]
        if fives:
            problems.append(f"B: {len(fives)} client-visible failures "
                            f"through the kill+repair window, first: "
                            f"{fives[0]}")
        doc = _status(fdp)
        if doc["scale_events"] > 1:
            problems.append(f"B: {doc['scale_events']} scale events for "
                            f"one dead replica — the cooldown did not "
                            f"hold")
        events = _events(fd_rsl, 90)
        ups = [e for e in events
               if e.get("name") == "controller/scale_up"]
        if not ups:
            problems.append("B: no controller/scale_up telemetry event")
        elif "min_world" not in str(
                ups[0].get("attrs", {}).get("reason", "")):
            problems.append(f"B: scale_up reason is not the min_world "
                            f"repair: {ups[0]}")
        names = [e.get("name") for e in events]
        for needed in ("frontdoor/eject", "frontdoor/readmit"):
            if needed not in names:
                problems.append(f"B: telemetry event {needed!r} missing "
                                f"— the kill/recovery was not recorded")
        print(f"rollout gate B: {len(results)} client requests, "
              f"{len(fives)} failures, scale_events="
              f"{doc['scale_events']}")
    finally:
        if load is not None:
            load.stop()
        if fd is not None:
            _stop(fd[0], fd[1], problems, "B: frontdoor", 30.0)
        # SIGTERM rank 0: the shutdown vote rides the health agreement,
        # so the controller-launched joiner (not our child) stops too
        _stop(rank0[0], rank0[1], problems, "B: rank 0", 120.0)
        if joiner is not None and joiner[0].poll() is None:
            joiner[0].kill()
            joiner[0].wait()
        if joiner is not None:
            joiner[1].close()
        subprocess.run(["pkill", "-f", rsl_b],
                       capture_output=True)  # stray joiner, if any
    return problems


# -- stage C: clean control — nothing to do, nothing done --------------

def stage_clean_control(work, rsl, cache, head):
    problems = []
    base = _free_block(2)
    mb = _free_block(2)
    fdp = _free_port()
    fd_rsl = os.path.join(work, "fd_c")
    reps = []
    for i in range(2):
        rrsl = os.path.join(work, f"rsl_c{i}")
        reps.append(_launch(
            _serve_cmd(rrsl, head["path"], base + i, cache,
                       metrics_port=mb + i),
            os.path.join(work, f"serve_c{i}.log")))
    fd = None
    load = None
    try:
        for i, (proc, log) in enumerate(reps):
            if not _wait_live(base + i, proc, LIVE_WAIT_S,
                              f"replica {i}"):
                return [f"C: replica {i} never went live on "
                        f":{base + i}\n{_tail(log.name)}"]
        fd = _launch(
            [sys.executable, MAIN, "frontdoor", "--rsl_path", fd_rsl,
             "--port", str(fdp), "--ranks", "2",
             "--serve-port", str(base), "--metrics-port", str(mb),
             "--interval", "0.3", "--rollout", "--watch-dir", rsl,
             "--autoscale", "--min-world", "2", "--max-world", "2",
             "--queue-low", "0", "--down-hold", "30"],
            os.path.join(work, "frontdoor_c.log"))
        doc = _wait_status(
            fdp, lambda d: all(d["upstreams"][str(i)]["alive"]
                               for i in (0, 1)), 60.0)
        if not doc:
            return [f"C: front door never came up\n{_tail(fd[1].name)}"]
        load = _Load(fdp).start()
        time.sleep(6.0)
        results = load.stop()
        load = None
        bad = [(s, b) for s, b in results if s != 200]
        if bad:
            problems.append(f"C: {len(bad)} non-200 answers on a "
                            f"healthy fleet, first: {bad[0]}")
        doc = _status(fdp)
        ro = doc["rollout"]
        if ro["rollbacks"] or ro["promotions"] \
                or ro["phase"] != "stable":
            problems.append(f"C: the watcher acted on a fleet already "
                            f"serving the ledger head: {ro}")
        if doc["scale_events"]:
            problems.append(f"C: {doc['scale_events']} scale events on "
                            f"a healthy, idle-enough fleet")
        for i in (0, 1):
            got = (doc["upstreams"][str(i)]["lineage"] or {}) \
                .get("sha256")
            if got != head["sha256"]:
                problems.append(f"C: replica {i} reports lineage "
                                f"{got!r}, expected the head "
                                f"{head['sha256'][:12]}")
        # satellite: every trace record carries the served lineage id
        tpath = os.path.join(work, "rsl_c0", "trace-rank0.jsonl")
        recs = []
        try:
            with open(tpath, encoding="utf-8") as f:
                recs = [json.loads(x) for x in f if x.strip()]
        except (OSError, ValueError) as e:
            problems.append(f"C: cannot read replica traces: {e}")
        want = head["sha256"][:12]
        unstamped = [r for r in recs if r.get("lineage") != want]
        if not recs:
            problems.append("C: no trace records at all")
        elif unstamped:
            problems.append(f"C: {len(unstamped)}/{len(recs)} trace "
                            f"records missing the serving lineage "
                            f"{want!r}, first: {unstamped[0]}")
        print(f"rollout gate C: {len(results)} requests all clean, "
              f"{len(recs)} trace records stamped {want}")
    finally:
        if load is not None:
            load.stop()
        if fd is not None:
            _stop(fd[0], fd[1], problems, "C: frontdoor", 30.0)
        for i, (proc, log) in enumerate(reps):
            _stop(proc, log, problems, f"C: replica {i}")
    return problems


def main() -> int:
    work = tempfile.mkdtemp(prefix="rollout_gate_")
    rsl = os.path.join(work, "rsl")
    cache = os.path.join(rsl, "xla_cache")

    t0 = time.perf_counter()
    train = subprocess.run(
        [sys.executable, MAIN, "train", "-d", "/nodata",
         "--dataset", "synthetic", "--model", "mlp", "-b", "8",
         "-e", "2", "--keep-ckpts", "2", "--rsl_path", rsl],
        cwd=REPO, env=_env(), capture_output=True, text=True)
    if train.returncode != 0:
        print(f"PROBLEM: provenance training run failed "
              f"rc={train.returncode}:\n{train.stdout[-800:]}\n"
              f"{train.stderr[-800:]}", file=sys.stderr)
        return 1
    head = newest_lineage_entry(rsl)
    problems = []
    if head is None:
        problems.append(f"no ledger head in {rsl}/{LINEAGE_FILE}")
    stable = stable_sha = None
    if not problems:
        # the STABLE is the oldest verified ledger entry that is not
        # the head — the --keep-ckpts 2 rotation must have kept it
        try:
            with open(os.path.join(rsl, LINEAGE_FILE)) as f:
                led = json.load(f)
            older = [
                r for r in led["records"]
                if r.get("sha256") and r["sha256"] != head["sha256"]
                and os.path.isfile(os.path.join(rsl,
                                                str(r.get("file", ""))))]
            rec = min(older, key=lambda r: int(r.get("epoch", 1 << 30)))
            stable = os.path.join(rsl, rec["file"])
            stable_sha = rec["sha256"]
        except (OSError, ValueError, KeyError) as e:
            problems.append(f"no older lineage-verified checkpoint to "
                            f"act as the stable (head "
                            f"{head['file']}): {e!r}")
    if problems:
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        return 1
    print(f"rollout gate 0: lineage trained in "
          f"{time.perf_counter() - t0:.1f}s — stable "
          f"{stable_sha[:12]}, head {head['file']} "
          f"({head['sha256'][:12]})")

    problems += stage_canary_rollback(work, rsl, cache, stable,
                                      stable_sha, head)
    problems += stage_kill_and_join(work, rsl, cache, head)
    problems += stage_clean_control(work, rsl, cache, head)

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("rollout gate OK: bad canary rolled back with zero client "
          "500s and the sha blacklisted; a SIGKILLed replica was "
          "ejected, repaired via --elastic-join and readmitted while "
          "clients saw only 200s; a fleet already on the ledger head "
          "drew zero rollbacks and zero scale events, every trace "
          "stamped with the served lineage")
    return 0


if __name__ == "__main__":
    sys.exit(main())
