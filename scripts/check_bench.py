#!/usr/bin/env python3
"""Bench provenance gate (VERDICT r5 weak #1).

Validates every committed ``BENCH_*.json`` headline against the
machine-readable ``fresh`` flag bench.py now emits:

  * ``fresh: false`` (a replayed last-known measurement — e.g. the TPU
    tunnel was down) must NEVER carry a ``vs_baseline`` value: a stale
    number compared against a fresh torch baseline is not a measurement.
  * a row carrying an ``error`` field must be flagged ``fresh: false``.

Rows written before the flag existed (no ``fresh`` key) are reported but
tolerated — the gate hardens from this PR forward without rewriting
history.  Exit 0 = clean, 1 = violation.
"""

import glob
import json
import os
import sys


def check_row(path: str, row: dict) -> list:
    problems = []
    if "fresh" not in row:
        print(f"  {os.path.basename(path)}: legacy row (no 'fresh' flag) "
              f"— tolerated")
        return problems
    if row["fresh"] is False and row.get("vs_baseline") is not None:
        problems.append(
            f"{path}: replayed measurement (fresh=false) must not "
            f"populate vs_baseline (got {row['vs_baseline']!r})")
    if row.get("error") and row["fresh"] is not False:
        problems.append(
            f"{path}: row carries an error ({row['error'][:60]}...) but "
            f"is not flagged fresh=false")
    return problems


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    problems = []
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except ValueError as e:
            problems.append(f"{path}: unreadable JSON ({e})")
            continue
        if isinstance(data, dict) and "metric" in data:
            problems += check_row(path, data)
        elif isinstance(data, dict) and isinstance(data.get("tail"), str):
            # driver round files wrap the headline in a log tail; the
            # last JSON-looking line is the bench output
            for line in reversed(data["tail"].strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        problems += check_row(path, json.loads(line))
                    except ValueError:
                        pass
                    break
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if not problems:
        print(f"bench provenance OK ({len(paths)} file(s) checked)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
