#!/usr/bin/env python3
"""Accuracy parity: this framework vs the reference's training loop, on the
SAME corpus, seed, batch size, and epoch budget.

The reference's own run recipe (ref classif.py:75-243: CNN + Adam(1e-3) +
CE, batch 64, 2 epochs, train/valid/test with best-model tracking) is
re-created faithfully in torch on host CPU — the only hardware the
reference can use in this environment — including its per-sample transform
pipeline (ref dataloader.py:98-116: RandomRotation(5, NEAREST, fill 0) ->
RandomResizedCrop(bilinear) -> 3-channel repeat -> Normalize), implemented
with PIL exactly as torchvision implements it (torchvision is not installed
here).  Ours runs through the real CLI drivers (run_train/run_test).

Corpus: real MNIST IDX files when present under --data-path (fetch with
scripts/fetch_mnist.sh on a machine with egress; this environment has
none), else the deterministic synthetic corpus — BOTH sides always see the
identical arrays and the identical 90/10 split, so the two final accuracy
columns are directly comparable either way.

Output: one JSON line with both sides' valid/test accuracies + a markdown
row for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------- corpus --

def load_corpus(dataset: str, data_path: str, seed: int):
    """(train, valid, test) Splits + mean/std — via the framework's own
    loader so both sides share arrays, stats, and the 90/10 split."""
    from distributedpytorch_tpu.data.datasets import load_dataset

    ds = load_dataset(dataset, data_path, seed,
                      synthetic_fallback=dataset.startswith("synthetic"))
    return ds


# ------------------------------------------------------- reference (torch) --

def run_reference(ds, epochs: int, batch: int, seed: int,
                  train_limit: int, optimizer: str = "adam",
                  init: str = "torch") -> dict:
    """The reference's train()+test() flow, faithfully (ref classif.py),
    with its transform pipeline done per-sample in PIL on host CPU."""
    import torch
    import torch.nn as nn
    import torch.nn.functional as F
    from PIL import Image

    torch.manual_seed(seed)
    np_rng = np.random.default_rng(seed)
    py_rng = random.Random(seed)

    mean, std = ds.mean, ds.std
    size = ds.splits["train"].images.shape[1]  # 28

    def to_tensor(arr_f32: np.ndarray) -> torch.Tensor:
        x = torch.from_numpy(arr_f32 / 255.0).float()
        x = x[None].repeat(3, 1, 1)            # TensorRepeat(3)
        return (x - mean) / std                # Normalize

    def train_transform(img_u8: np.ndarray) -> torch.Tensor:
        im = Image.fromarray(img_u8, mode="L")
        # RandomRotation(5, fill=0): torchvision default NEAREST resample.
        angle = py_rng.uniform(-5.0, 5.0)
        im = im.rotate(angle, resample=Image.NEAREST, fillcolor=0)
        # RandomResizedCrop(size): torchvision's sampling loop.
        area = size * size
        for _ in range(10):
            target = area * py_rng.uniform(0.08, 1.0)
            ratio = math.exp(py_rng.uniform(math.log(3 / 4), math.log(4 / 3)))
            w = int(round(math.sqrt(target * ratio)))
            h = int(round(math.sqrt(target / ratio)))
            if 0 < w <= size and 0 < h <= size:
                top = py_rng.randint(0, size - h)
                left = py_rng.randint(0, size - w)
                break
        else:
            w = h = min(size, size)
            top = (size - h) // 2
            left = (size - w) // 2
        im = im.crop((left, top, left + w, top + h)).resize(
            (size, size), Image.BILINEAR)
        return to_tensor(np.asarray(im, dtype=np.float32))

    def eval_transform(img_u8: np.ndarray) -> torch.Tensor:
        # Resize(size) -> CenterCrop(size): identity at native resolution.
        return to_tensor(img_u8.astype(np.float32))

    class SmallCNNTorch(nn.Module):
        """Same topology as the framework's flagship 'cnn'."""

        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(3, 32, 3, padding=1)
            self.c2 = nn.Conv2d(32, 32, 3, padding=1)
            self.c3 = nn.Conv2d(32, 64, 3, padding=1)
            self.c4 = nn.Conv2d(64, 64, 3, padding=1)
            self.fc1 = nn.Linear(64 * (size // 4) ** 2, 256)
            self.head = nn.Linear(256, ds.nb_classes)

        def forward(self, x):
            x = F.relu(self.c2(F.relu(self.c1(x))))
            x = F.max_pool2d(x, 2)
            x = F.relu(self.c4(F.relu(self.c3(x))))
            x = F.max_pool2d(x, 2)
            return self.head(F.relu(self.fc1(x.flatten(1))))

    model = SmallCNNTorch()
    if init == "lecun":
        # Diagnostic CONTROL, not the reference recipe: flax-style init
        # (lecun-normal weights, zero biases) on the torch model —
        # isolates whether an SGD learning gap is an init effect
        # (torch's kaiming-uniform(a=sqrt(5)) + uniform biases) rather
        # than an optimizer-dynamics divergence.
        for m in model.modules():
            if isinstance(m, (nn.Conv2d, nn.Linear)):
                fan_in = (m.weight[0].numel()  # in_ch * kH * kW
                          if isinstance(m, nn.Conv2d)
                          else m.weight.shape[1])
                nn.init.normal_(m.weight, std=fan_in ** -0.5)
                nn.init.zeros_(m.bias)
    # ref classif.py:122-131: Adam(1e-3) or SGD(1e-3, momentum 0.9) +
    # StepLR(step_size=1, gamma=0.1) stepped per epoch (SGD only)
    scheduler = None
    if optimizer == "sgd":
        opt = torch.optim.SGD(model.parameters(), lr=1e-3, momentum=0.9)
        scheduler = torch.optim.lr_scheduler.StepLR(opt, step_size=1,
                                                    gamma=0.1)
    else:
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = nn.CrossEntropyLoss()

    tr = ds.splits["train"]
    n_train = len(tr) if train_limit <= 0 else min(train_limit, len(tr))

    def run_epoch(split, training: bool, limit: int = 0) -> tuple:
        n = len(split) if limit <= 0 else min(limit, len(split))
        order = np_rng.permutation(len(split))[:n] if training \
            else np.arange(n)
        model.train(training)
        total_loss, correct, seen = 0.0, 0, 0
        tf = train_transform if training else eval_transform
        with torch.set_grad_enabled(training):
            for s in range(0, n, batch):
                idx = order[s:s + batch]
                x = torch.stack([tf(split.images[i]) for i in idx])
                y = torch.from_numpy(
                    split.labels[idx].astype(np.int64))
                if training:
                    opt.zero_grad()
                out = model(x)
                loss = criterion(out, y)
                if training:
                    loss.backward()
                    opt.step()
                total_loss += float(loss.detach()) * len(idx)
                correct += int((out.argmax(1) == y).sum())
                seen += len(idx)
        return total_loss / seen, correct / seen

    import copy

    best_valid = float("inf")
    valid_acc_at_best = 0.0
    best_state = copy.deepcopy(model.state_dict())
    tr_acc = float("nan")
    valid_loss_curve, valid_acc_curve = [], []
    t0 = time.monotonic()
    for epoch in range(epochs):
        tr_loss, tr_acc = run_epoch(tr, True, n_train)
        va_loss, va_acc = run_epoch(ds.splits["valid"], False)
        log(f"[ref] epoch {epoch}: train loss {tr_loss:.4f} "
            f"acc {tr_acc:.4f} | valid loss {va_loss:.4f} acc {va_acc:.4f}")
        valid_loss_curve.append(round(va_loss, 5))
        valid_acc_curve.append(round(va_acc, 5))
        if va_loss < best_valid:
            best_valid, valid_acc_at_best = va_loss, va_acc
            # snapshot like the reference's bestmodel checkpoint
            # (ref classif.py:188-192), so the test column evaluates the
            # best-valid model — symmetric with ours' best-checkpoint load.
            best_state = copy.deepcopy(model.state_dict())
        if scheduler is not None:  # ref classif.py:168-169
            scheduler.step()
    model.load_state_dict(best_state)
    te_loss, te_acc = run_epoch(ds.splits["test"], False)
    log(f"[ref] test acc {te_acc:.4f} ({time.monotonic() - t0:.0f}s)")
    return {"valid_acc": valid_acc_at_best, "test_acc": te_acc,
            "train_acc_final": tr_acc,
            "valid_loss_curve": valid_loss_curve,
            "valid_acc_curve": valid_acc_curve,
            "seconds": time.monotonic() - t0}


# ------------------------------------------------------------------- ours --

def run_ours(dataset: str, data_path: str, epochs: int, batch: int,
             seed: int, rsl: str, train_limit: int,
             optimizer: str = "adam", data_mode: str = "auto") -> dict:
    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu.cli import run_test, run_train
    from distributedpytorch_tpu.config import Config

    if train_limit > 0:
        log("[ours] note: --train-limit applies only to the reference side "
            "(ours trains the full split; limit exists to cap torch-CPU "
            "wall-clock)")
    t0 = time.monotonic()
    cfg = Config(action="train", data_path=data_path, rsl_path=rsl,
                 dataset=dataset, model_name="cnn", batch_size=batch,
                 nb_epochs=epochs, seed=seed,
                 # the framework spells it like the reference (config.py
                 # OPTIMIZER_CHOICES: 'adam' | 'SGD')
                 optimizer="SGD" if optimizer == "sgd" else optimizer,
                 data_mode=data_mode,
                 synthetic_fallback=dataset.startswith("synthetic"))
    result = run_train(cfg)
    best = ckpt.best_model_path(rsl, dataset, "cnn")
    test = run_test(Config(action="test", data_path=data_path, rsl_path=rsl,
                           dataset=dataset, batch_size=batch, seed=seed,
                           checkpoint_file=best,
                           synthetic_fallback=dataset.startswith(
                               "synthetic")))
    hist = result["history"]
    best_epoch = min(hist, key=lambda h: h["valid_loss"])
    log(f"[ours] valid acc {best_epoch['valid_acc']:.4f}, "
        f"test acc {test['test_acc']:.4f} ({time.monotonic() - t0:.0f}s)")
    return {"valid_acc": best_epoch["valid_acc"],
            "test_acc": test["test_acc"],
            "train_acc_final": hist[-1]["train_acc"],
            "valid_loss_curve": [round(h["valid_loss"], 5) for h in hist],
            "valid_acc_curve": [round(h["valid_acc"], 5) for h in hist],
            "seconds": time.monotonic() - t0}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default=None,
                   help="mnist|fashion_mnist|synthetic|synthetic_hard "
                        "(default: mnist if raw files exist under "
                        "--data-path, else synthetic_hard — the "
                        "non-saturating corpus, io.py SYNTH_HARD)")
    p.add_argument("--data-path", default="./data")
    p.add_argument("--epochs", type=int, default=2)  # ref config.py:38
    p.add_argument("--batch", type=int, default=64)  # ref config.py:40
    p.add_argument("--seed", type=int, default=1234)  # ref config.py:44
    p.add_argument("--rsl", default="/tmp/parity_rsl")
    p.add_argument("--train-limit", type=int, default=0,
                   help="cap reference-side train samples/epoch (torch-CPU "
                        "wall-clock control; 0 = full split)")
    p.add_argument("--optimizer", choices=("adam", "sgd"), default="adam",
                   help="both sides: adam(1e-3) or sgd(1e-3, momentum .9) "
                        "+ per-epoch StepLR(gamma .1) (ref "
                        "classif.py:122-131)")
    p.add_argument("--ref-init", choices=("torch", "lecun"),
                   default="torch",
                   help="reference-side weight init: 'torch' (the real "
                        "reference, torchvision defaults) or 'lecun' "
                        "(flax-style control — diagnostic only)")
    p.add_argument("--data-mode", choices=("auto", "stream", "resident"),
                   default="auto",
                   help="ours-side data mode.  'stream' matters on slow "
                        "single-core hosts: the resident whole-epoch scan "
                        "compiles to pathological XLA-CPU code there "
                        "(~26 s/step vs ~0.45 s/step streaming, measured) "
                        "while the two modes are numerics-identical "
                        "(tests/test_resident.py)")
    p.add_argument("--skip-ours", action="store_true")
    p.add_argument("--skip-reference", action="store_true")
    args = p.parse_args()
    if args.epochs < 1:
        p.error("--epochs must be >= 1")

    dataset = args.dataset
    if dataset is None:
        from distributedpytorch_tpu.data import io
        try:
            io.load_mnist_like(args.data_path, "MNIST")
            dataset = "mnist"
        except FileNotFoundError:
            log("no real MNIST under --data-path; using the hard synthetic "
                "corpus (fetch real files with scripts/fetch_mnist.sh)")
            dataset = "synthetic_hard"

    ds = load_corpus(dataset, args.data_path, args.seed)
    ours = (None if args.skip_ours else
            run_ours(dataset, args.data_path, args.epochs, args.batch,
                     args.seed, args.rsl, args.train_limit,
                     args.optimizer, args.data_mode))
    ref = (None if args.skip_reference else
           run_reference(ds, args.epochs, args.batch, args.seed,
                         args.train_limit, args.optimizer,
                         args.ref_init))

    out = {"dataset": dataset, "epochs": args.epochs, "batch": args.batch,
           "seed": args.seed, "train_limit": args.train_limit,
           "optimizer": args.optimizer, "ref_init": args.ref_init,
           "ours": ours, "reference": ref}
    if ours and ref:
        out["test_acc_delta"] = round(ours["test_acc"] - ref["test_acc"], 4)
        log(f"| {dataset} ({args.epochs} epochs, batch {args.batch}) "
            f"| ours {ours['test_acc'] * 100:.2f}% "
            f"| reference {ref['test_acc'] * 100:.2f}% "
            f"| delta {out['test_acc_delta'] * 100:+.2f}pp |")
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
