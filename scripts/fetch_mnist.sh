#!/usr/bin/env bash
# Fetch the raw MNIST / FashionMNIST IDX files into DATA_DIR, laid out the
# way torchvision (and therefore this framework's IDX reader,
# distributedpytorch_tpu/data/io.py) expects:
#
#   $DATA_DIR/MNIST/raw/{train,t10k}-{images-idx3,labels-idx1}-ubyte
#   $DATA_DIR/FashionMNIST/raw/...
#
# Usage:  scripts/fetch_mnist.sh [DATA_DIR]           (default: ./data)
#
# This environment has no network egress, so the script cannot run here —
# it documents the exact fetch for any machine that has egress.  Sources
# are the standard public mirrors (yann.lecun.com is rate-limited; the
# Google CVDF mirror hosts identical files).
set -euo pipefail

DATA_DIR="${1:-./data}"
MNIST_URL="https://storage.googleapis.com/cvdf-datasets/mnist"
FASHION_URL="http://fashion-mnist.s3-website.eu-central-1.amazonaws.com"

fetch() { # fetch <base_url> <out_dir>
  local base="$1" out="$2" f
  mkdir -p "$out"
  for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
           t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
    [ -f "$out/$f" ] && { echo "have $out/$f"; continue; }
    echo "fetching $base/$f.gz"
    curl -fsSL "$base/$f.gz" -o "$out/$f.gz"
    gunzip -f "$out/$f.gz"
  done
}

fetch "$MNIST_URL" "$DATA_DIR/MNIST/raw"
fetch "$FASHION_URL" "$DATA_DIR/FashionMNIST/raw"
echo "done: $DATA_DIR"
