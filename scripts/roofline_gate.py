#!/usr/bin/env python3
"""Roofline attribution gate leg (scripts/gate.sh), on CPU.

Three stages, all bounded (~1 min total):

  A. capture + attribute — a 2-epoch synthetic CPU run with --profile
     (plus AOT warmup so the traced epoch is steady-state) must leave
     RSL/roofline.json behind via the in-run auto-analysis, with
     >= 90% of traced device step time attributed to named ops, every
     op row carrying a compute/memory bound class and its class_source,
     and a ``roofline`` telemetry event for the timeline merge.
  B. CLI round trip — ``main.py roofline`` re-analyzes the same trace
     offline; its --json output must agree with the persisted artifact
     (same op count, coverage within float noise) and the human table
     must name the residual explicitly.
  C. anomaly path — a capture dir shaped like flightrec's output
     (trace files + manifest.json) under RSL/anomaly_traces; ``main.py
     roofline --from-anomaly`` must pick the newest capture and carry
     the trigger manifest into the report.

The bench-trend ledger has its own gate leg (scripts/bench_trend.py
against the checked-in BENCH history); this file is profiler-side only.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/roofline_gate.py``.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COVERAGE_MIN = 0.90


def _subenv():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config
    from distributedpytorch_tpu import roofline, telemetry

    problems = []
    work = tempfile.mkdtemp(prefix="roofline_gate_")
    rsl = os.path.join(work, "rsl")

    # -- stage A: profiled run -> in-run auto-analysis ----------------
    run_train(Config(action="train", data_path="/nodata", rsl_path=rsl,
                     dataset="synthetic", model_name="mlp", batch_size=8,
                     nb_epochs=2, debug=True, half_precision=False,
                     telemetry=True, profile=True, aot_warmup=True))

    trace_dir = os.path.join(rsl, "trace")
    if not roofline.find_trace_files(trace_dir):
        problems.append(f"--profile left no trace files under {trace_dir}")
    doc = None
    try:
        with open(os.path.join(rsl, "roofline.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        problems.append(f"in-run auto-analysis wrote no readable "
                        f"roofline.json ({e})")
    if doc:
        if doc["coverage"] < COVERAGE_MIN:
            problems.append(
                f"coverage {doc['coverage']:.1%} < {COVERAGE_MIN:.0%} — "
                f"too much traced step time is unattributed")
        if not doc["ops"]:
            problems.append("roofline.json has no op rows")
        for r in doc["ops"]:
            if r.get("bound") not in ("compute", "memory"):
                problems.append(f"op {r.get('name')!r} has no bound "
                                f"class: {r.get('bound')!r}")
                break
            if r.get("class_source") not in ("analytic", "heuristic"):
                problems.append(f"op {r.get('name')!r} has no "
                                f"class_source")
                break
        if doc["residual_us"] < 0:
            problems.append("negative unattributed residual")
        n_analytic = sum(1 for r in doc["ops"]
                         if r.get("class_source") == "analytic")
        if n_analytic == 0:
            problems.append(
                "no op joined against analytic HLO costs — the "
                "costs.json hlo capture or the join is broken")
        evs = telemetry.load_events(os.path.join(rsl, "telemetry"))
        roofs = [e for e in evs if e.get("kind") == "event"
                 and e.get("name") == "roofline"]
        if not roofs:
            problems.append("no `roofline` telemetry event — the "
                            "timeline merge has nothing to annotate")
        print(f"roofline gate A: coverage {doc['coverage']:.1%}, "
              f"{doc['n_ops']} ops ({n_analytic} analytic), residual "
              f"{doc['residual_us'] / 1e3:.2f} ms")

    # -- stage B: offline CLI round trip ------------------------------
    rep = subprocess.run([sys.executable, "main.py", "roofline",
                          "--rsl_path", rsl, "--json"], cwd=REPO,
                         env=_subenv(), capture_output=True, text=True)
    if rep.returncode != 0:
        problems.append(f"`main.py roofline --json` exited "
                        f"{rep.returncode}: {rep.stderr[-300:]}")
    elif doc:
        try:
            redoc = json.loads(rep.stdout)
        except ValueError:
            problems.append("`main.py roofline --json` printed "
                            "non-JSON output")
            redoc = None
        if redoc:
            if redoc["n_ops"] != doc["n_ops"] or \
                    abs(redoc["coverage"] - doc["coverage"]) > 1e-6:
                problems.append(
                    f"offline re-analysis disagrees with the in-run "
                    f"artifact: {redoc['n_ops']} ops at "
                    f"{redoc['coverage']:.4f} vs {doc['n_ops']} at "
                    f"{doc['coverage']:.4f}")
    rep_h = subprocess.run([sys.executable, "main.py", "roofline",
                            "--rsl_path", rsl], cwd=REPO, env=_subenv(),
                           capture_output=True, text=True)
    if rep_h.returncode != 0 or \
            "unattributed residual" not in rep_h.stdout:
        problems.append("human-mode `main.py roofline` is missing the "
                        "explicit unattributed-residual line")
    else:
        print("roofline gate B: offline round trip agrees with the "
              "in-run artifact")

    # -- stage C: --from-anomaly on a flightrec-shaped capture --------
    cap = os.path.join(rsl, "anomaly_traces", "capture-0")
    shutil.copytree(trace_dir, os.path.join(cap, "trace"))
    with open(os.path.join(cap, "manifest.json"), "w") as f:
        json.dump({"trigger": {"trigger": "step_time_spike"},
                   "epoch": 1, "step": 7, "capture": 0,
                   "capture_steps": 4}, f)
    rep_a = subprocess.run([sys.executable, "main.py", "roofline",
                            "--rsl_path", rsl, "--from-anomaly"],
                           cwd=REPO, env=_subenv(),
                           capture_output=True, text=True)
    if rep_a.returncode != 0:
        problems.append(f"`main.py roofline --from-anomaly` exited "
                        f"{rep_a.returncode}: {rep_a.stderr[-300:]}")
    elif "step_time_spike" not in rep_a.stdout:
        problems.append("--from-anomaly report does not carry the "
                        "capture's trigger manifest")
    else:
        print("roofline gate C: anomaly capture analyzed with its "
              "trigger manifest attached")

    shutil.rmtree(work, ignore_errors=True)
    if problems:
        for p in problems:
            print(f"roofline gate FAIL: {p}")
        return 1
    print("roofline gate GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
