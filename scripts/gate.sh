#!/usr/bin/env bash
# Pre-commit / pre-snapshot gate (VERDICT r3 item 2): the canary check
# that MUST be green before any commit touching shard_map / engine /
# model code — precisely the check that round 3 skipped when it shipped
# a red multichip gate.
#
#   1. static analysis: graftlint (the framework rule catalog — see
#      README "Static analysis & sanitizers"; suppressions need a
#      rationale) and ruff (generic baseline, [tool.ruff] in
#      pyproject.toml; leg skips with a notice when ruff is absent)
#   2. canary tests (~4.5 min on this single-core host): the components a
#      sharding/engine change can break — pipeline schedule + numerics,
#      sharded==big-batch equivalence, engine mechanics, driver entry
#   2b. scan gate: --scan-layers numerics (vit + densenet grads allclose
#      vs the unrolled loop), bidirectional cross-layout orbax restore,
#      >=3x densenet HLO-instruction reduction — see scripts/scan_gate.py
#      and README "Input pipelining & scan-over-layers"
#   3. transfer-guard smoke: one CPU streaming epoch with device->host
#      syncs disallowed outside the sanctioned per-epoch points — the
#      runtime sanitizer for the paper's per-batch .item() bug class
#   4. precision gate: the PrecisionPolicy contract — per-preset loss
#      parity vs f32, f32 accumulators proven from telemetry, fused
#      train step bit-identical to the two-dispatch path in f32 — see
#      scripts/precision_gate.py and README "Precision policy, fused
#      step & remat"
#   5. chaos gate: a short CPU run under a canned fault plan (transient
#      read errors, mid-run SIGTERM, torn head checkpoint, two-rank
#      fatal fault) proving every failure path recovers — see
#      scripts/chaos_gate.py and README "Fault tolerance & chaos testing"
#   6. anomaly gate: deterministic stall -> anomaly event + exactly one
#      programmatic profiler capture + flight-record dump; clean-run
#      false-positive check; recorder overhead budget; 2-rank timeline
#      merge — see scripts/anomaly_gate.py and README "Flight recorder,
#      anomaly profiling & timeline"
#   7. goodput gate: the wall-clock ledger must account >=99% of a
#      canned badput run (stall -> data_wait, ckpt retries ->
#      retry_backoff), serve valid live /metrics while the run is
#      alive, surface the timeline category track, and stay inside the
#      exporter overhead budget — see scripts/goodput_gate.py and
#      README "Goodput & live monitoring"
#   8. elastic gate: a 3-process gloo world with --elastic loses a rank
#      mid-epoch; survivors must shrink to 2, resume from the newest
#      snapshot, and finish with params allclose-identical to an
#      uninterrupted 2-rank reference — see scripts/chaos_gate.py
#      --stage elastic and README "Elastic training"
#   9. grow gate: stage 8's shrink, then scale-UP — a fourth process
#      with --elastic-join rejoins the shrunken world; survivors must
#      grow back to 3, resume from the newest 2-world snapshot, and
#      finish with params allclose-identical to an uninterrupted
#      3-rank reference — see scripts/chaos_gate.py --stage grow and
#      README "Elastic training"
#  10. roofline gate: a profiled 2-epoch CPU run must attribute >=90%
#      of traced device step time to named ops, classify every op
#      compute- vs memory-bound, and round-trip through
#      ``main.py roofline`` (incl. --from-anomaly) — see
#      scripts/roofline_gate.py and README "Roofline attribution &
#      bench trends"
#  11. bench-trend gate: the committed BENCH_r*.json history must pass
#      its own regression ledger — deltas only between fresh rows,
#      latest fresh-vs-fresh delta within threshold — see
#      scripts/bench_trend.py
#  12. serve gate: a 2-bucket ``main.py serve`` replica under a real
#      localhost load generator — client p95 + throughput floors, live
#      dpt_serve_* /metrics scraped mid-load, X-DPT-Request-Id on
#      every 200 with trace records reconciling against client
#      latencies, a fleet collector's merged series matching the
#      per-replica scrape, saturation answered with counted 503 sheds
#      (never hung clients), SIGTERM drain — see scripts/serve_gate.py
#      and README "Serving"
#  13. serve-chaos gate: two serve replicas in a 2-rank elastic gloo
#      world; an injected batch ioerror answers 500 and the tier keeps
#      serving, a rank_loss vanishes replica 1 mid-batch, the survivor
#      reconfigures (purpose=serve) and keeps answering on its port —
#      see scripts/chaos_gate.py --stage serve and README "Serving"
#  14. fleet gate: a ``main.py fleet`` collector over a 2-rank serve
#      world under a declarative error-rate SLO — clean control writes
#      zero incidents, an injected infer fault burst writes exactly
#      one bundle naming the failing rank + its request ids, a rank
#      loss ages out of the fleet series — see scripts/chaos_gate.py
#      --stage fleet and README "Fleet observability & SLOs"
#  15. rollout gate: ``main.py frontdoor`` over real serve replicas —
#      a fault-injected canary checkpoint auto-rolls back with zero
#      client-visible 500s and its sha blacklisted, a SIGKILLed
#      replica is ejected and repaired via the controller's
#      --elastic-join launch while clients keep seeing 200s, and a
#      fleet already serving the ledger head draws zero rollbacks and
#      zero scale events — see scripts/rollout_gate.py and README
#      "Front door, autoscaling & rollout"
#  16. sim gate: the deterministic fleet simulator replaying the REAL
#      control-plane policies at N=100 — control scenario (zero scale
#      actions / incidents / drops, byte-identical same-seed replay)
#      then chaos (stall wave + 30% preemption + ioerror burst +
#      canary rollout) against the robustness floors, with the
#      artifacts re-parsed by the live telemetry/tracing/goodput/
#      timeline pipelines — see scripts/sim_gate.py and README
#      "Fleet simulator"
#  17. the driver's own gate: __graft_entry__.dryrun_multichip(8)
#      (clean env, exactly as the driver runs it)
#
# Tier map:
#   pytest -m "not slow"   full fast tier (~20 min) — run before snapshots
#   pytest tests/          everything incl. subprocess worlds (~40+ min)
#
# Usage: bash scripts/gate.sh
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== gate: graftlint static analysis =="
# whole repo, all rules (not --changed-only: the gate is the place the
# FULL interprocedural pass must hold), then assert the whole-program
# rules are actually active — a refactor that silently drops them from
# the catalog must fail here, not ship a weaker gate.
python scripts/graftlint.py
python scripts/graftlint.py --json > /tmp/graftlint_gate.json
python - /tmp/graftlint_gate.json <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
missing = {"collective-divergence", "lock-order-cycle",
           "mesh-axis-propagation", "outbound-call-without-timeout",
           "nondeterminism-in-policy"} - set(payload["rules"])
assert not missing, f"whole-program rules inactive: {sorted(missing)}"
assert payload["findings"] == [], payload["findings"]
print(f"whole-program rules active ({len(payload['rules'])} total), "
      f"repo clean")
PY

echo "== gate: ruff (generic lint baseline) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check distributedpytorch_tpu tests scripts main.py bench.py \
        __graft_entry__.py
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check distributedpytorch_tpu tests scripts main.py \
        bench.py __graft_entry__.py
else
    echo "ruff not installed — leg skipped ([tool.ruff] in pyproject"
    echo "defines the contract; install ruff to enforce locally)"
fi

echo "== gate: canary tests =="
python -m pytest tests/test_pipeline.py tests/test_distributed.py \
    tests/test_graft_entry.py tests/test_engine.py -q -x -m "not slow"

echo "== gate: bench provenance (fresh flag) =="
python scripts/check_bench.py

echo "== gate: overlap regression (telemetry) =="
env -u XLA_FLAGS -u JAX_PLATFORMS python scripts/overlap_gate.py

echo "== gate: scan-layers (numerics / checkpoints / compile cost) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/scan_gate.py

echo "== gate: transfer-guard smoke (runtime sanitizer) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/graftlint.py --smoke

echo "== gate: precision (preset parity / f32 accum / fused step) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/precision_gate.py

echo "== gate: chaos (fault injection / retry / lineage recovery) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py

echo "== gate: anomaly (flight recorder / capture / timeline) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/anomaly_gate.py

echo "== gate: goodput (wall-clock ledger / live metrics) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/goodput_gate.py

echo "== gate: elastic (rank loss / shrink / resume parity) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py --stage elastic

echo "== gate: grow (rejoin / scale-up / resume parity) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py --stage grow

echo "== gate: roofline (per-op attribution / bound classes) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/roofline_gate.py

echo "== gate: bench trend (regression ledger on checked-in history) =="
python scripts/bench_trend.py

echo "== gate: serve (latency floors / live metrics / shed) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/serve_gate.py

echo "== gate: serve-chaos (batch fault / rank loss / survivor) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py --stage serve

echo "== gate: fleet (SLO burn rate / incidents / age-out) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py --stage fleet

echo "== gate: rollout (canary rollback / kill+join repair / clean) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/rollout_gate.py

echo "== gate: sim (fleet simulator at N=100 / floors / replay) =="
env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/sim_gate.py

echo "== gate: dryrun_multichip(8) =="
env -u XLA_FLAGS -u JAX_PLATFORMS python -c \
  "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

echo "== gate GREEN =="
