#!/usr/bin/env python3
"""Where does the cnn/b64 step's time go?  (VERDICT round-2 item #1)

Builds the exact headline-bench program (resident loader, cnn, batch 64,
synthetic corpus, seed 1234) and times a ladder of partial programs, each a
jitted lax.scan over the same epoch plan:

  gather            index-gather of the batch from the resident corpus
  + augment         + the fused affine-warp train transform
  + forward         + model apply (train mode) and loss
  + backward        + value_and_grad (no optimizer)
  full step         the real train_epoch (adds adam update + metrics)

Stage-to-stage deltas attribute the time.  Every program consumes its
result into a scalar carry so XLA cannot dead-code anything.  Run on the
TPU (default backend); writes PROFILE_BREAKDOWN.json at the repo root and
prints one human-readable table to stderr.

Usage: python scripts/profile_breakdown.py [--batch 64] [--steps 2814]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--steps", type=int, default=0,
                   help="scan length; 0 = 3 fused epochs like the bench")
    p.add_argument("--model", default="cnn")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import peak_flops, provenance_block, _make_corpus
    from distributedpytorch_tpu import costs, runtime, utils
    from distributedpytorch_tpu.data import augment
    from distributedpytorch_tpu.data.pipeline import ResidentLoader
    from distributedpytorch_tpu.models import get_model, get_model_input_size
    from distributedpytorch_tpu.ops import flops as flops_mod
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh()
    dataset = _make_corpus(28, 1, 60000)
    loader = ResidentLoader(dataset.splits["train"], mesh, args.batch,
                            shuffle=True, seed=1234)
    model = get_model(args.model, dataset.nb_classes)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, len(loader), False)
    engine = Engine(model, args.model, get_loss_fn("cross_entropy"), tx,
                    dataset.mean, dataset.std,
                    get_model_input_size(args.model))
    state = jax.device_put(
        engine.init_state(utils.root_key(1234)),
        runtime.replicated_sharding(mesh))
    key = utils.root_key(1234)

    # Timing-mode discipline (round-4 tunnel characterization, see
    # bench._force_sync_timing_mode): the runtime pipelines dispatches
    # until the process's first device->host transfer, after which every
    # dispatch is synchronous with a FIXED ~146 ms cost.  The ladder's
    # 2814-step scans amortize that to ~52 us/step of PHANTOM overhead
    # on every absolute row (the previously-reported "scan_overhead_us"
    # ~48 was exactly this) — stage DELTAS cancel it.  We force the sync
    # mode up front so rows are at least deterministic, and report the
    # empty-scan row as the fixed-cost baseline to subtract.
    from bench import _force_sync_timing_mode

    _force_sync_timing_mode()
    if args.steps <= 0:
        idx_k, valid_k = loader.epoch_plan_many(range(3))
        idx = idx_k.reshape(-1, idx_k.shape[-1])
        valid = valid_k.reshape(-1, valid_k.shape[-1])
    else:
        idx, valid = loader.epoch_plan(0)
        idx, valid = idx[:args.steps], valid[:args.steps]
    n_steps = int(idx.shape[0])
    images_all, labels_all = loader.images, loader.labels
    mean, std = engine.mean, engine.std
    out_dim = engine.input_size
    cdt = engine.compute_dtype
    device_kind = jax.devices()[0].device_kind
    # Honest MFU: the peak denominator matches the run's compute dtype
    # (ops/flops.py per-dtype table); the report records which one.
    peak_dtype = flops_mod.dtype_label(cdt)
    peak = peak_flops(device_kind, peak_dtype)
    gb = loader.global_batch

    # --- the ladder of partial programs (each: scan, scalar carry) -------

    def stage_empty(acc, xs):
        ids, v = xs
        return acc + jnp.sum(v) + jnp.sum(ids) * 0, None

    def stage_gather(acc, xs):
        ids, v = xs
        im = jnp.take(images_all, ids, axis=0)
        lb = jnp.take(labels_all, ids, axis=0)
        return acc + jnp.sum(im.astype(jnp.float32)) + jnp.sum(lb) \
            + jnp.sum(v), None

    def stage_augment(acc, xs):
        ids, v = xs
        im = jnp.take(images_all, ids, axis=0)
        lb = jnp.take(labels_all, ids, axis=0)
        aug = augment.train_transform(key, im, mean, std, out_dim,
                                      out_dtype=cdt)
        return acc + jnp.sum(aug.astype(jnp.float32)) + jnp.sum(lb) \
            + jnp.sum(v), None

    def _loss_of(params, ids, v):
        im = jnp.take(images_all, ids, axis=0)
        lb = jnp.take(labels_all, ids, axis=0)
        aug = augment.train_transform(key, im, mean, std, out_dim,
                                      out_dtype=cdt)
        out, _, _ = engine._apply(params, state.batch_stats, aug, True, key)
        # aux-logit models (inception) return (logits, aux_logits) in
        # train mode; the ladder profiles the main head only
        logits = out[0] if isinstance(out, tuple) else out
        vmask = v.astype(jnp.float32)
        return engine._reduce_loss(logits, lb, vmask)

    def stage_forward(acc, xs):
        ids, v = xs
        return acc + _loss_of(state.params, ids, v), None

    def stage_backward(acc, xs):
        ids, v = xs
        loss, grads = jax.value_and_grad(_loss_of)(state.params, ids, v)
        g0 = sum(jnp.sum(g) for g in jax.tree_util.tree_leaves(grads))
        return acc + loss + g0 * 0.0, None

    def run_scan(body):
        # The plan is passed as an ARGUMENT (constants embedded in the
        # executable are one avoidable variable), but the dominant term
        # in every ABSOLUTE row here is the sync-mode fixed dispatch
        # cost described above (~40-50 us/step at this scan length —
        # the empty_scan row measures exactly that baseline); only
        # stage-to-stage DELTAS attribute per-step work.
        fn = jax.jit(lambda i, v: jax.lax.scan(body, jnp.zeros(()),
                                               (i, v))[0])
        fn(idx, valid).block_until_ready()  # compile + warmup
        t0 = time.monotonic()
        fn(idx, valid).block_until_ready()
        return (time.monotonic() - t0) / n_steps

    results = {}
    for name, body in [("empty_scan", stage_empty),
                       ("gather", stage_gather),
                       ("gather_augment", stage_augment),
                       ("gather_augment_fwd", stage_forward),
                       ("gather_augment_fwd_bwd", stage_backward)]:
        per_step = run_scan(body)
        results[name] = per_step
        log(f"{name:26s} {per_step * 1e6:8.1f} us/step")

    # full program: the real train_epoch (AOT-compiled like the bench);
    # its XLA cost estimate goes into the shared registry (costs.py) so
    # this report and the runtime MFU gauge quote the same numbers.
    compiled = engine.train_epoch.lower(
        state, images_all, labels_all, idx, valid, key).compile()
    costs.record("train_epoch", compiled, hlo=True)
    st, m = compiled(state, images_all, labels_all, idx, valid, key)
    jax.block_until_ready(m["loss"])
    t0 = time.monotonic()
    st, m = compiled(st, images_all, labels_all, idx, valid, key)
    jax.block_until_ready(m["loss"])
    results["full_step"] = (time.monotonic() - t0) / n_steps
    log(f"{'full_step':26s} {results['full_step'] * 1e6:8.1f} us/step")

    # roofline inputs AFTER all timed runs (device_get degrades later
    # dispatches — see the note above); shapes come from the returned
    # state (the input state was donated).
    host_params = jax.device_get(st.params)
    host_bs = jax.device_get(st.batch_stats)
    fps = flops_mod.train_flops_per_sample(
        engine.model, host_params, host_bs, batch=gb, input_size=out_dim)
    costs.record_analytic("train_flops_per_sample", flops_per_sample=fps,
                          note="profile_breakdown analytic (ops.flops)")
    if peak is not None:
        costs.record_mfu_denominator(peak, peak_dtype, device_kind)
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(host_params))

    # attribution by deltas
    breakdown = {
        "scan_overhead_us": results["empty_scan"] * 1e6,
        "gather_us": (results["gather"] - results["empty_scan"]) * 1e6,
        "augment_us": (results["gather_augment"] - results["gather"]) * 1e6,
        "forward_us": (results["gather_augment_fwd"]
                       - results["gather_augment"]) * 1e6,
        "backward_us": (results["gather_augment_fwd_bwd"]
                        - results["gather_augment_fwd"]) * 1e6,
        "optimizer_metrics_us": (results["full_step"]
                                 - results["gather_augment_fwd_bwd"]) * 1e6,
        "full_step_us": results["full_step"] * 1e6,
    }

    # Per-stage bound classification — the SAME classifier roofline.py
    # applies per op (shared roofline.bound_class), fed with analytic
    # stage-level FLOPs/bytes estimates: gather/augment move the batch
    # without matmul work; forward is 1/3 and backward 2/3 of the
    # train-step model FLOPs (the standard split ops/flops.py uses);
    # optimizer+metrics touch every param ~8x (adam reads/writes
    # params + both moments) for a handful of FLOPs each.
    from distributedpytorch_tpu.roofline import bound_class

    el_bytes = np.dtype(np.float32).itemsize
    batch_elems = float(gb * out_dim * out_dim * dataset.channels)
    params_bytes = float(n_params * el_bytes)
    stage_costs = {
        "gather_us": (0.0, 2.0 * batch_elems * el_bytes),
        "augment_us": (10.0 * batch_elems, 2.0 * batch_elems * el_bytes),
        "forward_us": (fps * gb / 3.0,
                       params_bytes + batch_elems * el_bytes),
        "backward_us": (fps * gb * 2.0 / 3.0, 3.0 * params_bytes),
        "optimizer_metrics_us": (10.0 * n_params, 8.0 * params_bytes),
    }
    stage_classes = {}
    for stage, (sf, sb) in stage_costs.items():
        cls = bound_class(sf, sb, device_kind, peak_dtype, stage)
        stage_classes[stage] = {
            "bound": cls["bound"], "class_source": cls["class_source"],
            "arithmetic_intensity": cls["arithmetic_intensity"],
            "ridge_flops_per_byte": cls["ridge_flops_per_byte"],
            "ridge_source": cls["ridge_source"],
        }

    # roofline context
    ideal_us = fps * gb / peak * 1e6 if peak else None
    out = {
        "model": args.model, "batch": args.batch, "steps": n_steps,
        "device_kind": device_kind,
        # Same provenance block as bench.py (ISSUE 12): a stale
        # PROFILE_BREAKDOWN.json can't masquerade as current.
        **provenance_block(fresh=True),
        "stage_us_per_step": {k: round(v * 1e6, 2)
                              for k, v in results.items()},
        "breakdown_us": {k: round(v, 2) for k, v in breakdown.items()},
        "stage_bound_class": stage_classes,
        "train_flops_per_step": fps * gb,
        "ideal_matmul_us_at_peak": round(ideal_us, 2) if ideal_us else None,
        "mfu": (fps * gb / (results["full_step"] * peak)) if peak else None,
        "mfu_peak_dtype": peak_dtype,
        "mfu_peak_flops_per_chip": peak,
        "n_params": n_params,
        # both methodologies, provenance-stamped (costs.py)
        "cost_registry": costs.registry(),
    }
    log("")
    log(f"breakdown (us/step, batch {gb}, {device_kind}):")
    for k, v in breakdown.items():
        cls = stage_classes.get(k)
        tag = f"   {cls['bound']}-bound" if cls else ""
        log(f"  {k:24s} {v:8.1f}{tag}")
    if ideal_us:
        log(f"  {'ideal_at_peak':24s} {ideal_us:8.1f}   "
            f"(analytic FLOPs / {peak / 1e12:.0f} TF/s {peak_dtype})")
        log(f"  MFU {out['mfu'] * 100:.1f}%")

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "PROFILE_BREAKDOWN.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    log(f"wrote {path}")
    saved = costs.save(root)
    if saved:
        log(f"wrote {saved}")
    print(json.dumps(out["breakdown_us"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
