#!/usr/bin/env python3
"""Chaos gate leg (scripts/gate.sh): every failure path, end to end.

Four stages, all CPU and bounded:

  A. reference — a fault-free 3-epoch synthetic run; its final params
     are the recovery target.
  B. chaos — the same run under a canned fault plan: two transient
     dataset-read errors (must be retried, with ``retry/attempts`` in
     the telemetry), a mid-run SIGTERM during epoch 1's rolling save
     (must preempt cleanly at the epoch boundary), and a torn write of
     that same rolling file (head checkpoint left corrupt on disk).
  C. resume — restart from the TORN head: the lineage fallback must
     reject it loudly (``ckpt_fallback`` event), fall back to the
     epoch-0 snapshot, finish the remaining epochs, and land on final
     params equal to the reference run's.
  D. failure agreement — two real processes (gloo rendezvous) with a
     fatal fault injected on rank 0 only: BOTH ranks must exit nonzero
     within the deadline (no hang), and both telemetry JSONLs must
     carry the ``peer_failure`` event.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py``.
The script re-execs itself with ``--child`` for stage D's ranks.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 3
CHILD_EXIT = 7          # the children's agreed-failure exit status
CHILD_DEADLINE_S = 420.0

CHAOS_PLAN = {
    "seed": 0,
    "faults": [
        # Transient dataset reads: retried, never fatal.
        {"site": "data.read", "kind": "ioerror", "after_n": 0, "count": 2},
        # ckpt.save/ckpt.finalize hit order is deterministic: epoch 0
        # writes rolling (hit 1) then best (hit 2, best always improves
        # from inf); epoch 1's rolling save is hit 3 on both sites.
        {"site": "ckpt.save", "kind": "preempt", "after_n": 2, "count": 1},
        {"site": "ckpt.finalize", "kind": "torn", "after_n": 2, "count": 1,
         "path_match": "checkpoint-"},
    ],
}


def _events(rsl: str, rank: int = 0) -> list:
    path = os.path.join(rsl, "telemetry", f"rank{rank}.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _named(events: list, name: str) -> list:
    return [e for e in events
            if e.get("kind") == "event" and e.get("name") == name]


def _base_cfg(rsl: str):
    from distributedpytorch_tpu.config import Config

    return Config(action="train", data_path="/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="mlp", batch_size=8,
                  nb_epochs=EPOCHS, debug=True, half_precision=False,
                  telemetry=True, keep_ckpts=EPOCHS)


def _params(result) -> list:
    import jax
    import numpy as np

    return [np.asarray(jax.device_get(leaf)) for leaf in
            jax.tree_util.tree_leaves(result["state"].params)]


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu import telemetry
    from distributedpytorch_tpu.cli import run_train

    problems = []
    work = tempfile.mkdtemp(prefix="chaos_gate_")

    # -- stage A: fault-free reference --------------------------------
    ref = run_train(_base_cfg(os.path.join(work, "ref")))
    ref_params = _params(ref)
    print(f"chaos gate A: reference run done "
          f"({len(ref['history'])} epochs)")

    # -- stage B: transients + preempt + torn head --------------------
    plan_path = os.path.join(work, "plan.json")
    with open(plan_path, "w") as f:
        json.dump(CHAOS_PLAN, f)
    chaos_rsl = os.path.join(work, "chaos")
    chaos = run_train(_base_cfg(chaos_rsl).replace(fault_plan=plan_path))
    ev = _events(chaos_rsl)
    agg = telemetry.aggregate(ev)
    if not chaos["preempted"]:
        problems.append("chaos run was not preempted — the injected "
                        "SIGTERM (ckpt.save preempt fault) was lost")
    if len(chaos["history"]) != 2:
        problems.append(f"chaos run finished {len(chaos['history'])} "
                        f"epochs, expected 2 (preempt after epoch 1)")
    if agg["counters"].get("retry/attempts", 0) < 2:
        problems.append("retry/attempts < 2 — the transient data.read "
                        "faults were not retried (or not counted)")
    if agg["counters"].get("retry/giveups", 0):
        problems.append("retry/giveups > 0 — a transient fault "
                        "exhausted the retry policy")
    fired = _named(ev, "fault_injected")
    kinds = sorted(e["attrs"]["kind"] for e in fired)
    if kinds != ["ioerror", "ioerror", "preempt", "torn"]:
        problems.append(f"fault_injected events {kinds} != the planned "
                        f"[ioerror x2, preempt, torn]")
    if not _named(ev, "preempt"):
        problems.append("no preempt event — the SIGTERM was not "
                        "surfaced at the epoch boundary")
    head = ckpt.checkpoint_path(chaos_rsl, "synthetic", "mlp", 1)
    if ckpt.verify_checkpoint(head) is None:
        problems.append(f"head checkpoint {head} verifies clean — the "
                        f"torn fault did not corrupt it")
    print(f"chaos gate B: chaos run preempted after "
          f"{len(chaos['history'])} epochs, "
          f"{int(agg['counters'].get('retry/attempts', 0))} retries, "
          f"head torn")

    # -- stage C: resume from the torn head ---------------------------
    resume = run_train(_base_cfg(chaos_rsl).replace(checkpoint_file=head))
    ev = _events(chaos_rsl)
    fallbacks = _named(ev, "ckpt_fallback")
    if not fallbacks:
        problems.append("no ckpt_fallback event — the torn head was not "
                        "loudly rejected on resume")
    resumed_epochs = [h["epoch"] for h in resume["history"]]
    if resumed_epochs != [1, 2]:
        problems.append(f"resume ran epochs {resumed_epochs}, expected "
                        f"[1, 2] (fallback to the epoch-0 snapshot)")
    res_params = _params(resume)
    if len(res_params) != len(ref_params) or not all(
            np.allclose(a, b, rtol=1e-5, atol=1e-6)
            for a, b in zip(ref_params, res_params)):
        problems.append("resumed final params differ from the "
                        "fault-free reference run's — recovery is not "
                        "bit-compatible")
    print(f"chaos gate C: resumed past torn head "
          f"({len(fallbacks)} fallback event(s)), params match "
          f"reference")

    # -- stage D: two-rank fatal-failure agreement --------------------
    problems += _stage_fatal_agreement(work, plan_dir=work)

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("chaos gate OK: retries, preemption, torn-head fallback and "
          "multi-rank failure agreement all green")
    return 0


def _stage_fatal_agreement(work: str, plan_dir: str) -> list:
    """Stage D driver: spawn 2 ranks of this same script, rank 0 carrying
    a fatal fault at its first checkpoint save; both must exit CHILD_EXIT
    before the deadline and both JSONLs must carry peer_failure."""
    import socket

    problems = []
    plan_path = os.path.join(plan_dir, "fatal_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [{"site": "ckpt.save", "kind": "fatal",
                               "after_n": 0, "count": 1, "rank": 0}]}, f)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs, rsls = [], [], []
    for pid in range(2):
        rsl = os.path.join(work, f"fatal_rank{pid}")
        log = os.path.join(work, f"fatal_rank{pid}.log")
        rsls.append(rsl)
        logs.append(log)
        # A log FILE, never a pipe: an undrained pipe backpressures a
        # chatty child into blocking mid-collective and deadlocks both.
        out = open(log, "ab")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--coord", coord, "--pid", str(pid),
             "--plan", plan_path, "--rsl", rsl],
            cwd=REPO, env=env, stdout=out, stderr=out))

    deadline = time.monotonic() + CHILD_DEADLINE_S
    for pid, p in enumerate(procs):
        try:
            rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            problems.append(
                f"rank {pid} HUNG past {CHILD_DEADLINE_S:.0f}s — failure "
                f"agreement broken\n{_tail(logs[pid])}")
            continue
        if rc != CHILD_EXIT:
            problems.append(
                f"rank {pid} exited rc={rc}, expected {CHILD_EXIT} "
                f"(agreed fatal exit)\n{_tail(logs[pid])}")
    for pid, rsl in enumerate(rsls):
        try:
            if not _named(_events(rsl, rank=pid), "peer_failure"):
                problems.append(f"rank {pid} JSONL has no peer_failure "
                                f"event — the agreed exit left no trail")
        except OSError:
            problems.append(f"rank {pid} wrote no telemetry JSONL")
    if not problems:
        print("chaos gate D: both ranks exited the fatal fault "
              "together, peer_failure in both JSONLs")
    return problems


def _tail(path: str, n: int = 2500) -> str:
    try:
        return open(path).read()[-n:]
    except OSError:
        return "<no log>"


def child_main(a) -> int:
    """One stage-D rank: join the gloo rendezvous, train under the fatal
    plan, and exit CHILD_EXIT on the agreed failure path."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import faults, runtime
    from distributedpytorch_tpu.cli import run_train

    runtime.initialize_distributed(coordinator_address=a.coord,
                                   num_processes=2, process_id=a.pid)
    cfg = _base_cfg(a.rsl).replace(fault_plan=a.plan, nb_epochs=2,
                                   batch_size=4)
    try:
        run_train(cfg)
    except (faults.FatalFaultError, faults.PeerFailureError) as e:
        print(f"rank {a.pid}: agreed fatal exit: {e}", file=sys.stderr)
        return CHILD_EXIT
    print(f"rank {a.pid}: run finished WITHOUT the fatal fault firing",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--coord")
    ap.add_argument("--pid", type=int)
    ap.add_argument("--plan")
    ap.add_argument("--rsl")
    args = ap.parse_args()
    sys.exit(child_main(args) if args.child else main())
