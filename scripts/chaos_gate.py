#!/usr/bin/env python3
"""Chaos gate leg (scripts/gate.sh): every failure path, end to end.

Four stages, all CPU and bounded:

  A. reference — a fault-free 3-epoch synthetic run; its final params
     are the recovery target.
  B. chaos — the same run under a canned fault plan: two transient
     dataset-read errors (must be retried, with ``retry/attempts`` in
     the telemetry), a mid-run SIGTERM during epoch 1's rolling save
     (must preempt cleanly at the epoch boundary), and a torn write of
     that same rolling file (head checkpoint left corrupt on disk).
  C. resume — restart from the TORN head: the lineage fallback must
     reject it loudly (``ckpt_fallback`` event), fall back to the
     epoch-0 snapshot, finish the remaining epochs, and land on final
     params equal to the reference run's.
  D. failure agreement — two real processes (gloo rendezvous) with a
     fatal fault injected on rank 0 only: BOTH ranks must exit nonzero
     within the deadline (no hang), and both telemetry JSONLs must
     carry the ``peer_failure`` event.
  E. elastic (``--stage elastic``, its own gate.sh leg) — three real
     processes with --elastic; a ``rank_loss`` fault kills rank 2
     mid-epoch-1 (``os._exit``, no cleanup).  Ranks 0/1 must
     reconfigure into a 2-rank world, resume from the epoch-0
     snapshot, finish, and exit 0 — and their final checkpoint must
     equal (allclose) an uninterrupted 2-rank reference run resumed
     from a copy of the same epoch-0 snapshot.  Asserted from the
     shared run dir: ``elastic/reconfigure`` in both survivors'
     JSONLs, flight dumps carrying reason ``reconfigure``, rank 2
     exiting with the rank-loss status.
  F. grow (``--stage grow``, its own gate.sh leg) — stage E's shrink,
     then the scale-UP half: once the driver observes the shrink-to-2
     reconfigure in rank 0's JSONL, it launches a FOURTH process with
     ``--elastic-join``.  The joiner drops a join claim, the survivors
     admit it at the next health boundary and grow back to a 3-world,
     everyone resumes from the newest 2-world snapshot, and all of
     ranks 0/1/joiner finish and exit 0 (original rank 2 exits with
     the rank-loss status).  The grown world's final checkpoint must
     equal (allclose) an uninterrupted 3-rank reference run resumed
     from a copy of that same snapshot — proving restore-into-a-
     larger-mesh and the N+1 loader re-derivation end to end.
  G. serve (``--stage serve``, its own gate.sh leg) — the serving
     tier's failure paths (ISSUE 15), end to end over real HTTP: two
     ``main.py serve`` replicas in a 2-rank elastic gloo world.  An
     injected ``serve.infer`` ioerror on replica 0 must fail exactly
     ONE request's micro-batch (a 500 answer) and leave the tier
     serving; a ``serve.infer`` rank_loss on replica 1 vanishes it
     mid-batch — only that in-flight request dies with its socket.
     Replica 0 must then reconfigure (``elastic/reconfigure`` with
     ``purpose: "serve"`` and a 1-world) and KEEP ANSWERING on the
     same port, and SIGTERM must drain it to exit 0.
  H. fleet (``--stage fleet``, its own gate.sh leg) — fleet-scope
     observability (ISSUE 16): a ``main.py fleet`` collector scraping
     a 2-rank serve world under a declarative error-rate SLO.  A
     clean control run must produce ZERO incidents; an injected
     ``serve.infer`` ioerror burst on replica 1 must trip the
     multi-window burn rate into exactly ONE incident bundle naming
     rank 1 and its failed request ids; a follow-up rank loss must
     age the dead rank out of the fleet series (``dpt_up`` drops).

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/chaos_gate.py``
(stages A-D) or with ``--stage elastic`` / ``--stage grow`` /
``--stage serve`` / ``--stage fleet`` (one stage each).  The script
re-execs itself with ``--child`` for the multi-process stages' ranks.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 3
CHILD_EXIT = 7          # the children's agreed-failure exit status
CHILD_DEADLINE_S = 420.0

CHAOS_PLAN = {
    "seed": 0,
    "faults": [
        # Transient dataset reads: retried, never fatal.
        {"site": "data.read", "kind": "ioerror", "after_n": 0, "count": 2},
        # ckpt.save/ckpt.finalize hit order is deterministic: epoch 0
        # writes rolling (hit 1) then best (hit 2, best always improves
        # from inf); epoch 1's rolling save is hit 3 on both sites.
        {"site": "ckpt.save", "kind": "preempt", "after_n": 2, "count": 1},
        {"site": "ckpt.finalize", "kind": "torn", "after_n": 2, "count": 1,
         "path_match": "checkpoint-"},
    ],
}


def _events(rsl: str, rank: int = 0) -> list:
    path = os.path.join(rsl, "telemetry", f"rank{rank}.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _named(events: list, name: str) -> list:
    return [e for e in events
            if e.get("kind") == "event" and e.get("name") == name]


def _base_cfg(rsl: str):
    from distributedpytorch_tpu.config import Config

    return Config(action="train", data_path="/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="mlp", batch_size=8,
                  nb_epochs=EPOCHS, debug=True, half_precision=False,
                  telemetry=True, keep_ckpts=EPOCHS)


def _params(result) -> list:
    import jax
    import numpy as np

    return [np.asarray(jax.device_get(leaf)) for leaf in
            jax.tree_util.tree_leaves(result["state"].params)]


def main(stage: str = "core") -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu import telemetry
    from distributedpytorch_tpu.cli import run_train

    problems = []
    work = tempfile.mkdtemp(prefix="chaos_gate_")

    if stage == "elastic":
        problems = _stage_elastic(work)
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        if problems:
            return 1
        print("chaos gate OK: rank loss survived, world shrunk, resumed "
              "run matches the uninterrupted reference")
        return 0

    if stage == "grow":
        problems = _stage_grow(work)
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        if problems:
            return 1
        print("chaos gate OK: world shrank on rank loss, grew back on "
              "the rejoin, and the grown world matches the "
              "uninterrupted 3-rank reference")
        return 0

    if stage == "serve":
        problems = _stage_serve(work)
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        if problems:
            return 1
        print("chaos gate OK: serve replica survived the injected "
              "batch fault, the survivor reconfigured past the rank "
              "loss and kept answering, SIGTERM drained clean")
        return 0

    if stage == "fleet":
        problems = _stage_fleet(work)
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        if problems:
            return 1
        print("chaos gate OK: fault burst tripped the error SLO into "
              "exactly one incident naming the failing rank, the dead "
              "rank aged out of the fleet series, and the clean "
              "control produced zero incidents")
        return 0

    # -- stage A: fault-free reference --------------------------------
    ref = run_train(_base_cfg(os.path.join(work, "ref")))
    ref_params = _params(ref)
    print(f"chaos gate A: reference run done "
          f"({len(ref['history'])} epochs)")

    # -- stage B: transients + preempt + torn head --------------------
    plan_path = os.path.join(work, "plan.json")
    with open(plan_path, "w") as f:
        json.dump(CHAOS_PLAN, f)
    chaos_rsl = os.path.join(work, "chaos")
    chaos = run_train(_base_cfg(chaos_rsl).replace(fault_plan=plan_path))
    ev = _events(chaos_rsl)
    agg = telemetry.aggregate(ev)
    if not chaos["preempted"]:
        problems.append("chaos run was not preempted — the injected "
                        "SIGTERM (ckpt.save preempt fault) was lost")
    if len(chaos["history"]) != 2:
        problems.append(f"chaos run finished {len(chaos['history'])} "
                        f"epochs, expected 2 (preempt after epoch 1)")
    if agg["counters"].get("retry/attempts", 0) < 2:
        problems.append("retry/attempts < 2 — the transient data.read "
                        "faults were not retried (or not counted)")
    if agg["counters"].get("retry/giveups", 0):
        problems.append("retry/giveups > 0 — a transient fault "
                        "exhausted the retry policy")
    fired = _named(ev, "fault_injected")
    kinds = sorted(e["attrs"]["kind"] for e in fired)
    if kinds != ["ioerror", "ioerror", "preempt", "torn"]:
        problems.append(f"fault_injected events {kinds} != the planned "
                        f"[ioerror x2, preempt, torn]")
    if not _named(ev, "preempt"):
        problems.append("no preempt event — the SIGTERM was not "
                        "surfaced at the epoch boundary")
    head = ckpt.checkpoint_path(chaos_rsl, "synthetic", "mlp", 1)
    if ckpt.verify_checkpoint(head) is None:
        problems.append(f"head checkpoint {head} verifies clean — the "
                        f"torn fault did not corrupt it")
    print(f"chaos gate B: chaos run preempted after "
          f"{len(chaos['history'])} epochs, "
          f"{int(agg['counters'].get('retry/attempts', 0))} retries, "
          f"head torn")

    # -- stage C: resume from the torn head ---------------------------
    resume = run_train(_base_cfg(chaos_rsl).replace(checkpoint_file=head))
    ev = _events(chaos_rsl)
    fallbacks = _named(ev, "ckpt_fallback")
    if not fallbacks:
        problems.append("no ckpt_fallback event — the torn head was not "
                        "loudly rejected on resume")
    resumed_epochs = [h["epoch"] for h in resume["history"]]
    if resumed_epochs != [1, 2]:
        problems.append(f"resume ran epochs {resumed_epochs}, expected "
                        f"[1, 2] (fallback to the epoch-0 snapshot)")
    res_params = _params(resume)
    if len(res_params) != len(ref_params) or not all(
            np.allclose(a, b, rtol=1e-5, atol=1e-6)
            for a, b in zip(ref_params, res_params)):
        problems.append("resumed final params differ from the "
                        "fault-free reference run's — recovery is not "
                        "bit-compatible")
    print(f"chaos gate C: resumed past torn head "
          f"({len(fallbacks)} fallback event(s)), params match "
          f"reference")

    # -- stage D: two-rank fatal-failure agreement --------------------
    problems += _stage_fatal_agreement(work, plan_dir=work)

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("chaos gate OK: retries, preemption, torn-head fallback and "
          "multi-rank failure agreement all green")
    return 0


def _stage_fatal_agreement(work: str, plan_dir: str) -> list:
    """Stage D driver: spawn 2 ranks of this same script, rank 0 carrying
    a fatal fault at its first checkpoint save; both must exit CHILD_EXIT
    before the deadline and both JSONLs must carry peer_failure."""
    import socket

    problems = []
    plan_path = os.path.join(plan_dir, "fatal_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [{"site": "ckpt.save", "kind": "fatal",
                               "after_n": 0, "count": 1, "rank": 0}]}, f)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs, rsls = [], [], []
    for pid in range(2):
        rsl = os.path.join(work, f"fatal_rank{pid}")
        log = os.path.join(work, f"fatal_rank{pid}.log")
        rsls.append(rsl)
        logs.append(log)
        # A log FILE, never a pipe: an undrained pipe backpressures a
        # chatty child into blocking mid-collective and deadlocks both.
        out = open(log, "ab")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--coord", coord, "--pid", str(pid),
             "--plan", plan_path, "--rsl", rsl],
            cwd=REPO, env=env, stdout=out, stderr=out))

    deadline = time.monotonic() + CHILD_DEADLINE_S
    for pid, p in enumerate(procs):
        try:
            rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            problems.append(
                f"rank {pid} HUNG past {CHILD_DEADLINE_S:.0f}s — failure "
                f"agreement broken\n{_tail(logs[pid])}")
            continue
        if rc != CHILD_EXIT:
            problems.append(
                f"rank {pid} exited rc={rc}, expected {CHILD_EXIT} "
                f"(agreed fatal exit)\n{_tail(logs[pid])}")
    for pid, rsl in enumerate(rsls):
        try:
            if not _named(_events(rsl, rank=pid), "peer_failure"):
                problems.append(f"rank {pid} JSONL has no peer_failure "
                                f"event — the agreed exit left no trail")
        except OSError:
            problems.append(f"rank {pid} wrote no telemetry JSONL")
    if not problems:
        print("chaos gate D: both ranks exited the fatal fault "
              "together, peer_failure in both JSONLs")
    return problems


def _child_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch_world(work: str, tag: str, nprocs: int, rsls: list,
                  plan: str = None, elastic: bool = False,
                  epochs: int = 2, ckpt_file: str = None,
                  stream: bool = False) -> list:
    """Spawn ``nprocs`` ranks of this script as real processes over a
    gloo rendezvous; return [(rank, Popen, logpath)] WITHOUT waiting —
    the grow stage needs to act (launch a joiner) while the world
    runs."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    env = _child_env()
    procs = []
    for pid in range(nprocs):
        log = os.path.join(work, f"{tag}_rank{pid}.log")
        argv = [sys.executable, os.path.abspath(__file__), "--child",
                "--coord", coord, "--pid", str(pid),
                "--nprocs", str(nprocs), "--epochs", str(epochs),
                "--rsl", rsls[pid]]
        if plan:
            argv += ["--plan", plan]
        if elastic:
            argv += ["--elastic"]
        if ckpt_file:
            argv += ["--ckpt", ckpt_file]
        if stream:
            argv += ["--stream"]
        # A log FILE, never a pipe (see _stage_fatal_agreement).
        out = open(log, "ab")
        procs.append((pid, subprocess.Popen(argv, cwd=REPO, env=env,
                                            stdout=out, stderr=out),
                      log))
    return procs


def _await_world(procs: list) -> list:
    """[(rank, Popen, log)] -> [(rank, rc-or-None, log)] once all exit
    or the shared deadline lapses (hung ranks are killed, rc None)."""
    deadline = time.monotonic() + CHILD_DEADLINE_S
    results = []
    for pid, p, log in procs:
        try:
            rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            rc = None
        results.append((pid, rc, log))
    return results


def _spawn_world(work: str, tag: str, nprocs: int, rsls: list,
                 plan: str = None, elastic: bool = False,
                 epochs: int = 2, ckpt_file: str = None,
                 stream: bool = False) -> list:
    """_launch_world + _await_world, for the stages that just block."""
    return _await_world(_launch_world(
        work, tag, nprocs, rsls, plan=plan, elastic=elastic,
        epochs=epochs, ckpt_file=ckpt_file, stream=stream))


def _ckpt_state_leaves(path: str) -> list:
    """Numeric leaves of a msgpack checkpoint's model params, in
    deterministic tree order — world-size independent (files are written
    from the gathered/replicated state)."""
    from flax import serialization
    import jax
    import numpy as np

    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return [np.asarray(leaf) for leaf in
            jax.tree_util.tree_leaves(payload["state"]["params"])]


def _stage_elastic(work: str) -> list:
    """Stage E driver: 3 gloo ranks under --elastic; a rank_loss fault
    vanishes rank 2 mid-epoch-1 (after the epoch-0 snapshot lands).
    Ranks 0/1 must reconfigure to a 2-rank world, resume from that
    snapshot, finish all epochs and exit 0; rank 2 must exit with the
    rank-loss status.  The survivors' final checkpoint must equal an
    uninterrupted 2-rank reference resumed from a copy of the same
    epoch-0 snapshot."""
    import shutil

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu import flightrec
    from distributedpytorch_tpu.faults import RANK_LOSS_EXIT

    problems = []
    rsl_a = os.path.join(work, "elastic")
    os.makedirs(rsl_a, exist_ok=True)
    # Hit math (world 3, batch 4, --debug => 200-sample train AND valid
    # splits, streamed so data.host_batch is live): ceil(200/3/4) = 17
    # steps/epoch per split, so epoch 0 is host-batch hits 1..34
    # (train+valid), the epoch-0 checkpoint lands at that boundary, and
    # epoch 1's train pass is hits 35..51.  after_n=40 fires on hit 41
    # — train step 7 of epoch 1.
    plan_path = os.path.join(work, "rank_loss_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [{"site": "data.host_batch",
                               "kind": "rank_loss", "after_n": 40,
                               "count": 1, "rank": 2}]}, f)
    results = _spawn_world(work, "elastic", nprocs=3, rsls=[rsl_a] * 3,
                           plan=plan_path, elastic=True, epochs=EPOCHS,
                           stream=True)
    for pid, rc, log in results:
        want = RANK_LOSS_EXIT if pid == 2 else 0
        label = ("rank-loss exit" if pid == 2
                 else "survived + reconfigured")
        if rc is None:
            problems.append(f"elastic rank {pid} HUNG past "
                            f"{CHILD_DEADLINE_S:.0f}s\n{_tail(log)}")
        elif rc != want:
            problems.append(f"elastic rank {pid} exited rc={rc}, "
                            f"expected {want} ({label})\n{_tail(log)}")
    if problems:
        return problems

    # Survivors' trail: reconfigure event in BOTH telemetry JSONLs
    # (original rank files — telemetry keeps the pre-shrink rank id) and
    # a flight dump whose reasons include the reconfigure.
    for pid in (0, 1):
        try:
            evs = _named(_events(rsl_a, rank=pid), "elastic/reconfigure")
        except OSError:
            evs = []
        if not evs:
            problems.append(f"survivor rank {pid} has no "
                            f"elastic/reconfigure telemetry event")
        elif evs[0]["attrs"].get("new_world") != 2:
            problems.append(f"survivor rank {pid} reconfigured to world "
                            f"{evs[0]['attrs'].get('new_world')}, not 2")
    dumps = flightrec.load_dumps(rsl_a)
    for pid in (0, 1):
        reasons = dumps.get(pid, {}).get("reasons", [])
        if "reconfigure" not in reasons:
            problems.append(f"survivor rank {pid} flight dump reasons "
                            f"{reasons} lack 'reconfigure'")

    # Reference: a FRESH 2-rank world resumed from a copy of the very
    # snapshot the survivors fell back to.  No lineage ledger is copied
    # on purpose: pre-lineage files verify as None (loadable), and the
    # reference run then builds its own ledger in rsl_b.
    epoch0 = ckpt.checkpoint_path(rsl_a, "synthetic", "mlp", 0)
    if not os.path.exists(epoch0):
        return problems + [f"epoch-0 snapshot {epoch0} missing — the "
                           f"fault fired before the first checkpoint"]
    rsl_b = os.path.join(work, "elastic_ref")
    os.makedirs(rsl_b, exist_ok=True)
    ref0 = ckpt.checkpoint_path(rsl_b, "synthetic", "mlp", 0)
    shutil.copy2(epoch0, ref0)
    results = _spawn_world(work, "elastic_ref", nprocs=2,
                           rsls=[rsl_b] * 2, epochs=EPOCHS,
                           ckpt_file=ref0, stream=True)
    for pid, rc, log in results:
        if rc != 0:
            problems.append(f"reference rank {pid} exited rc={rc}, "
                            f"expected 0\n{_tail(log)}")
    if problems:
        return problems

    final_a = ckpt.checkpoint_path(rsl_a, "synthetic", "mlp", EPOCHS - 1)
    final_b = ckpt.checkpoint_path(rsl_b, "synthetic", "mlp", EPOCHS - 1)
    for path, who in ((final_a, "survivors"), (final_b, "reference")):
        if not os.path.exists(path):
            problems.append(f"{who} wrote no final checkpoint {path}")
    if problems:
        return problems
    pa, pb = _ckpt_state_leaves(final_a), _ckpt_state_leaves(final_b)
    if len(pa) != len(pb) or not all(
            np.allclose(a, b, rtol=1e-5, atol=1e-6)
            for a, b in zip(pa, pb)):
        problems.append("survivors' final params differ from the "
                        "uninterrupted 2-rank reference — the shrunken "
                        "world did not recover bit-compatibly")
    if not problems:
        print("chaos gate E: rank 2 vanished mid-epoch, ranks 0/1 "
              "reconfigured to world 2, resumed from the epoch-0 "
              "snapshot and matched the reference")
    return problems


GROW_EPOCHS = 5
SHRINK_WAIT_S = 240.0


def _wait_for_event(rsl: str, rank: int, name: str, pred,
                    timeout_s: float) -> dict:
    """Poll one rank's JSONL until an event matching ``pred`` lands (the
    writers flush elastic events eagerly) or the timeout lapses."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            evs = [e for e in _named(_events(rsl, rank=rank), name)
                   if pred(e)]
        except (OSError, ValueError):
            evs = []  # file not there yet, or a line torn mid-write
        if evs:
            return evs[0]
        time.sleep(1.0)
    return None


def _launch_joiner(work: str, tag: str, rsl: str, epochs: int):
    """Spawn one ``--elastic-join`` process against a live run's dir.
    No coordinator address and no nprocs: the joiner discovers the
    world through the join claim protocol, nothing else."""
    log = os.path.join(work, f"{tag}_joiner.log")
    argv = [sys.executable, os.path.abspath(__file__), "--child",
            "--join", "--pid", "3", "--epochs", str(epochs),
            "--rsl", rsl, "--stream"]
    out = open(log, "ab")
    return (3, subprocess.Popen(argv, cwd=REPO, env=_child_env(),
                                stdout=out, stderr=out), log)


def _stage_grow(work: str) -> list:
    """Stage F driver: stage E's rank loss, then scale back UP.  A
    3-rank elastic world loses rank 2 and shrinks to 2; the driver
    watches rank 0's JSONL for the shrink reconfigure, then launches a
    fourth process with --elastic-join.  The survivors must admit it at
    a health boundary, grow back to a 3-world and resume from the
    newest 2-world snapshot — and the grown world's final params must
    equal an uninterrupted 3-rank reference resumed from a copy of that
    same snapshot."""
    import shutil

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu.faults import RANK_LOSS_EXIT

    problems = []
    rsl_a = os.path.join(work, "grow")
    os.makedirs(rsl_a, exist_ok=True)
    # Hit math: same as stage E (rank 2 dies on host-batch hit 41 —
    # train step 7 of epoch 1).  The stall spec is the timing knob: a
    # stall is a pure sleep (numerics untouched), so slowing rank 0's
    # post-loss host batches by 0.25s each holds the shrunken world
    # open long enough for the driver to observe the shrink and for
    # the joiner's claim to land before the run ends.
    plan_path = os.path.join(work, "grow_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [
            {"site": "data.host_batch", "kind": "rank_loss",
             "after_n": 40, "count": 1, "rank": 2},
            {"site": "data.host_batch", "kind": "stall", "after_n": 34,
             "count": 250, "stall_s": 0.25, "rank": 0},
        ]}, f)
    procs = _launch_world(work, "grow", nprocs=3, rsls=[rsl_a] * 3,
                          plan=plan_path, elastic=True,
                          epochs=GROW_EPOCHS, stream=True)
    # Wait for the shrink BEFORE dropping the join claim: a claim
    # visible while rank 2 is still alive would grow the world to 4.
    shrunk = _wait_for_event(
        rsl_a, 0, "elastic/reconfigure",
        lambda e: e.get("attrs", {}).get("new_world") == 2,
        timeout_s=SHRINK_WAIT_S)
    if shrunk is None:
        for _, p, _ in procs:
            p.kill()
        return [f"grow stage: no shrink-to-2 reconfigure on rank 0 "
                f"within {SHRINK_WAIT_S:.0f}s\n{_tail(procs[0][2])}"]
    print("chaos gate F: shrink observed, launching the joiner")
    results = _await_world(procs + [_launch_joiner(work, "grow", rsl_a,
                                                   GROW_EPOCHS)])
    for pid, rc, log in results:
        want = RANK_LOSS_EXIT if pid == 2 else 0
        label = ("rank-loss exit" if pid == 2
                 else "survived the shrink-then-grow")
        if rc is None:
            problems.append(f"grow rank {pid} HUNG past "
                            f"{CHILD_DEADLINE_S:.0f}s\n{_tail(log)}")
        elif rc != want:
            problems.append(f"grow rank {pid} exited rc={rc}, expected "
                            f"{want} ({label})\n{_tail(log)}")
    if problems:
        return problems

    # Survivors' trail: shrink to 2 then grow to 3, in order, with the
    # grow reconfigure naming the joiner.
    for pid in (0, 1):
        try:
            evs = _named(_events(rsl_a, rank=pid), "elastic/reconfigure")
        except OSError:
            evs = []
        worlds = [e.get("attrs", {}).get("new_world") for e in evs]
        if worlds != [2, 3]:
            problems.append(f"survivor rank {pid} reconfigure worlds "
                            f"{worlds}, expected [2, 3]")
        elif not (evs[1]["attrs"].get("grow")
                  and evs[1]["attrs"].get("joined")):
            problems.append(f"survivor rank {pid} grow reconfigure "
                            f"lacks grow/joined attrs: "
                            f"{evs[1]['attrs']}")
    # The joiner took over rank 2's slot (and telemetry file, opened in
    # append): its birth certificate is the elastic/join event.
    try:
        joins = _named(_events(rsl_a, rank=2), "elastic/join")
    except OSError:
        joins = []
    if not joins or joins[0]["attrs"].get("new_world") != 3 \
            or joins[0]["attrs"].get("new_rank") != 2:
        problems.append(
            "no elastic/join event in the rejoined rank-2 stream "
            f"(got {[e.get('attrs') for e in joins]})")
    # Where did the grown world resume?  Generation 1 was the shrink,
    # generation 2 the grow; its elastic/resume names the start epoch.
    resumes = [e for e in _named(_events(rsl_a, rank=0), "elastic/resume")
               if e.get("attrs", {}).get("generation") == 2]
    if not resumes:
        return problems + ["no generation-2 elastic/resume event on "
                           "rank 0 — cannot locate the grow resume "
                           "point"]
    e_r = resumes[0]["attrs"].get("epoch")
    if not isinstance(e_r, int) or not 1 <= e_r < GROW_EPOCHS:
        return problems + [f"grow resume epoch {e_r!r} outside "
                           f"[1, {GROW_EPOCHS})"]
    if problems:
        return problems

    # Reference: an uninterrupted 3-rank world resumed from a copy of
    # the very snapshot the grown world restored — written by the
    # 2-world at epoch e_r - 1.  From e_r on, run A is a 3-world too,
    # with resharded loaders and a restored-into-a-larger-mesh state;
    # determinism makes the final params exactly comparable.
    snap = ckpt.checkpoint_path(rsl_a, "synthetic", "mlp", e_r - 1)
    if not os.path.exists(snap):
        return problems + [f"grow resume snapshot {snap} missing"]
    rsl_b = os.path.join(work, "grow_ref")
    os.makedirs(rsl_b, exist_ok=True)
    ref0 = ckpt.checkpoint_path(rsl_b, "synthetic", "mlp", e_r - 1)
    shutil.copy2(snap, ref0)
    results = _spawn_world(work, "grow_ref", nprocs=3, rsls=[rsl_b] * 3,
                           epochs=GROW_EPOCHS, ckpt_file=ref0,
                           stream=True)
    for pid, rc, log in results:
        if rc != 0:
            problems.append(f"grow reference rank {pid} exited rc={rc}, "
                            f"expected 0\n{_tail(log)}")
    if problems:
        return problems
    final_a = ckpt.checkpoint_path(rsl_a, "synthetic", "mlp",
                                   GROW_EPOCHS - 1)
    final_b = ckpt.checkpoint_path(rsl_b, "synthetic", "mlp",
                                   GROW_EPOCHS - 1)
    for path, who in ((final_a, "grown world"), (final_b, "reference")):
        if not os.path.exists(path):
            problems.append(f"{who} wrote no final checkpoint {path}")
    if problems:
        return problems
    pa, pb = _ckpt_state_leaves(final_a), _ckpt_state_leaves(final_b)
    if len(pa) != len(pb) or not all(
            np.allclose(a, b, rtol=1e-5, atol=1e-6)
            for a, b in zip(pa, pb)):
        problems.append("grown world's final params differ from the "
                        "uninterrupted 3-rank reference — the rejoin "
                        "did not recover bit-compatibly")
    if not problems:
        print(f"chaos gate F: shrank to 2 on the rank loss, grew back "
              f"to 3 on the rejoin (resumed at epoch {e_r}), matched "
              f"the reference")
    return problems


SERVE_LIVE_WAIT_S = 240.0


def _serve_post(port: int, timeout: float = 35.0):
    """One /predict round trip -> (status, body) — HTTPError unwrapped,
    transport-level death (the rank_loss shape) re-raised."""
    import urllib.error
    import urllib.request

    sample = [[(r * 28 + c) % 256 for c in range(28)] for r in range(28)]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": sample}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _serve_wait_live(port: int, proc, timeout_s: float) -> bool:
    import urllib.request

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/livez", timeout=5) as r:
                if json.loads(r.read()).get("ok"):
                    return True
        except (OSError, ValueError):
            time.sleep(0.5)
    return False


def _stage_serve(work: str) -> list:
    """Stage G driver: train a checkpoint, stand up a 2-rank elastic
    serve world (one replica per rank, port = base + rank), and walk
    the failure ladder over real HTTP: one injected batch ioerror on
    replica 0 (a 500, tier keeps serving), a rank_loss mid-batch on
    replica 1 (its in-flight request dies with the socket), then the
    survivor's reconfigure — it must keep answering on the same port
    and drain clean on SIGTERM."""
    import signal
    import socket

    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.faults import RANK_LOSS_EXIT

    problems = []
    # The checkpoint the replicas load, with its lineage ledger: a
    # 1-epoch in-process training run in the SHARED serve dir.
    rsl = os.path.join(work, "serve")
    os.makedirs(rsl, exist_ok=True)
    run_train(_base_cfg(rsl).replace(nb_epochs=1))
    ckpt_file = os.path.join(rsl, "bestmodel-synthetic-mlp.ckpt")
    if not os.path.exists(ckpt_file):
        return [f"provenance training run left no checkpoint at "
                f"{ckpt_file}"]

    # Replica 0: batch 2's infer raises (one 500, tier survives).
    # Replica 1: batch 3's infer is a rank loss (os._exit mid-dispatch).
    plan_path = os.path.join(work, "serve_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [
            {"site": "serve.infer", "kind": "ioerror", "after_n": 1,
             "count": 1, "rank": 0},
            {"site": "serve.infer", "kind": "rank_loss", "after_n": 2,
             "count": 1, "rank": 1},
        ]}, f)

    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    with socket.socket() as s:
        s.bind(("localhost", 0))
        base_port = s.getsockname()[1]

    env = _child_env()
    procs = []
    for pid in range(2):
        log = os.path.join(work, f"serve_rank{pid}.log")
        out = open(log, "ab")
        procs.append((pid, subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--serve", "--coord", coord, "--pid", str(pid),
             "--nprocs", "2", "--rsl", rsl, "--ckpt", ckpt_file,
             "--plan", plan_path, "--serve-port", str(base_port),
             "--elastic"],
            cwd=REPO, env=env, stdout=out, stderr=out), log))
    ports = {pid: base_port + pid for pid, _, _ in procs}

    try:
        for pid, p, log in procs:
            if not _serve_wait_live(ports[pid], p, SERVE_LIVE_WAIT_S):
                return [f"serve replica {pid} never went live on "
                        f":{ports[pid]}\n{_tail(log)}"]
        print("chaos gate G: both replicas live, walking the ladder")

        # Rung 1 — replica 0: 200, injected-500, 200.  One bad batch
        # fails ITS request and nothing else.
        seq = [_serve_post(ports[0]) for _ in range(3)]
        codes = [s for s, _ in seq]
        if codes != [200, 500, 200]:
            problems.append(f"replica 0 answered {codes} around the "
                            f"injected serve.infer ioerror, expected "
                            f"[200, 500, 200]")
        elif "injected" not in seq[1][1].get("error", ""):
            problems.append(f"replica 0's 500 does not carry the "
                            f"injected error: {seq[1][1]}")

        # Rung 2 — replica 1: two clean answers, then the rank loss
        # takes the replica AND the in-flight request's socket.
        for i in range(2):
            s, b = _serve_post(ports[1])
            if s != 200:
                problems.append(f"replica 1 request {i} answered {s} "
                                f"({b}) before any fault")
        try:
            s, b = _serve_post(ports[1], timeout=20.0)
            problems.append(f"replica 1's rank-loss request ANSWERED "
                            f"({s}, {b}) — the fault did not fire")
        except OSError:
            pass  # the expected shape: connection died mid-request
        rc1 = procs[1][1].wait(timeout=60)
        if rc1 != RANK_LOSS_EXIT:
            problems.append(f"replica 1 exited rc={rc1}, expected the "
                            f"rank-loss status {RANK_LOSS_EXIT}"
                            f"\n{_tail(procs[1][2])}")

        # Rung 3 — the survivor reconfigures (purpose tagged "serve",
        # world of 1) and keeps answering on its ORIGINAL port.
        rec = _wait_for_event(
            rsl, 0, "elastic/reconfigure",
            lambda e: e.get("attrs", {}).get("purpose") == "serve",
            timeout_s=180.0)
        if rec is None:
            problems.append(f"survivor replica 0 never logged a "
                            f"purpose=serve elastic/reconfigure"
                            f"\n{_tail(procs[0][2])}")
        elif rec["attrs"].get("new_world") != 1:
            problems.append(f"survivor reconfigured to world "
                            f"{rec['attrs'].get('new_world')}, not 1")
        answered_after = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if procs[0][1].poll() is not None:
                break
            try:
                answered_after = _serve_post(ports[0], timeout=20.0)
                if answered_after[0] == 200:
                    break
            except OSError:
                time.sleep(1.0)
        if not answered_after or answered_after[0] != 200:
            problems.append(f"survivor stopped answering after the "
                            f"reconfigure (last: {answered_after})"
                            f"\n{_tail(procs[0][2])}")

        # Rung 4 — drain: SIGTERM must exit 0 through the health tick.
        procs[0][1].send_signal(signal.SIGTERM)
        try:
            rc0 = procs[0][1].wait(timeout=90)
        except subprocess.TimeoutExpired:
            procs[0][1].kill()
            rc0 = None
        if rc0 != 0:
            problems.append(f"survivor exited rc={rc0} on SIGTERM, "
                            f"expected a clean 0\n{_tail(procs[0][2])}")
    finally:
        for _, p, _ in procs:
            if p.poll() is None:
                p.kill()

    # The audit trail: the injected faults in each replica's JSONL.
    try:
        fired0 = _named(_events(rsl, rank=0), "fault_injected")
    except OSError:
        fired0 = []
    if not any(e["attrs"].get("site") == "serve.infer"
               and e["attrs"].get("kind") == "ioerror" for e in fired0):
        problems.append("replica 0 JSONL lacks the serve.infer ioerror "
                        "fault_injected event")
    try:
        fired1 = _named(_events(rsl, rank=1), "fault_injected")
    except OSError:
        fired1 = []
    if not any(e["attrs"].get("site") == "serve.infer"
               and e["attrs"].get("kind") == "rank_loss"
               for e in fired1):
        problems.append("replica 1 JSONL lacks the serve.infer "
                        "rank_loss fault_injected event")
    if not problems:
        print("chaos gate G: 500-and-carry-on on replica 0, rank loss "
              "absorbed, survivor reconfigured and kept answering")
    return problems


def _stage_fleet(work: str) -> list:
    """Stage H driver: the fleet collector (ISSUE 16) watching a 2-rank
    serve world under a declarative error-rate SLO.  Clean control
    first — both replicas scraped, zero incidents.  Then the fault
    world: an injected ``serve.infer`` ioerror burst on replica 1 must
    trip the multi-window burn rate into EXACTLY one incident bundle
    naming rank 1 and its failed request ids, and a follow-up rank
    loss must age the dead rank out of the fleet series (``dpt_up``
    drops to 1 — never a stale self-report)."""
    import signal
    import socket
    import urllib.request

    from distributedpytorch_tpu import slo
    from distributedpytorch_tpu.cli import run_train

    problems = []
    rsl = os.path.join(work, "fleetworld")
    os.makedirs(rsl, exist_ok=True)
    run_train(_base_cfg(rsl).replace(nb_epochs=1))
    ckpt_file = os.path.join(rsl, "bestmodel-synthetic-mlp.ckpt")
    if not os.path.exists(ckpt_file):
        return [f"provenance training run left no checkpoint at "
                f"{ckpt_file}"]

    # Error-rate SLO: 90% target (10% budget), fast 2s window at 2x
    # burn AND slow 8s window at 1x — both sized so a 12-failure burst
    # against light clean traffic trips them within a few collector
    # cycles, while the clean control never comes near.
    spec_path = os.path.join(work, "slo.json")
    with open(spec_path, "w") as f:
        json.dump({"slos": [{
            "name": "serve-errors", "kind": "ratio",
            "bad": "dpt_serve_failed_total",
            "total": "dpt_serve_requests_total",
            "target": 0.9,
            "windows": [{"seconds": 2.0, "burn": 2.0},
                        {"seconds": 8.0, "burn": 1.0}]}]}, f)

    def launch(tag: str, world_rsl: str, plan_path):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            coord = f"localhost:{s.getsockname()[1]}"
        with socket.socket() as s:
            s.bind(("localhost", 0))
            base_port = s.getsockname()[1]
        with socket.socket() as s:
            s.bind(("localhost", 0))
            base_mport = s.getsockname()[1]
        with socket.socket() as s:
            s.bind(("localhost", 0))
            fport = s.getsockname()[1]
        env = _child_env()
        procs = []
        for pid in range(2):
            log = os.path.join(work, f"{tag}_rank{pid}.log")
            out = open(log, "ab")
            cmd = [sys.executable, os.path.abspath(__file__), "--child",
                   "--serve", "--coord", coord, "--pid", str(pid),
                   "--nprocs", "2", "--rsl", world_rsl,
                   "--ckpt", ckpt_file, "--serve-port", str(base_port),
                   "--metrics-port", str(base_mport), "--elastic"]
            if plan_path:
                cmd += ["--plan", plan_path]
            procs.append((pid, subprocess.Popen(
                cmd, cwd=REPO, env=env, stdout=out, stderr=out), log))
        flog = os.path.join(work, f"{tag}_fleet.log")
        coll = subprocess.Popen(
            [sys.executable, "main.py", "fleet",
             "--rsl_path", world_rsl,
             "--metrics-port", str(base_mport), "--ranks", "2",
             "--fleet-port", str(fport), "--interval", "0.25",
             "--stale-after", "4", "--slo-spec", spec_path],
            cwd=REPO, env=env, stdout=open(flog, "ab"),
            stderr=subprocess.STDOUT)
        return procs, coll, base_port, fport, flog

    def fleet_doc(fport: int):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/fleet", timeout=5) as r:
            return json.loads(r.read())

    def wait_alive(fport: int, want: list, timeout_s: float = 60.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                doc = fleet_doc(fport)
                if doc.get("alive") == want:
                    return doc
            except (OSError, ValueError):
                pass
            time.sleep(0.25)
        return None

    def teardown(procs, coll, tag: str):
        coll.terminate()
        try:
            coll.wait(timeout=15)
        except subprocess.TimeoutExpired:
            coll.kill()
            coll.wait()
        for _, p, _ in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for pid, p, _ in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=90)
                except subprocess.TimeoutExpired:
                    p.kill()
                    problems.append(f"{tag}: replica {pid} hung on "
                                    f"SIGTERM")

    # -- clean control: zero incidents --------------------------------
    ctl_rsl = os.path.join(work, "control")
    os.makedirs(ctl_rsl, exist_ok=True)
    procs, coll, base_port, fport, flog = launch("ctl", ctl_rsl, None)
    try:
        for pid, p, log in procs:
            if not _serve_wait_live(base_port + pid, p,
                                    SERVE_LIVE_WAIT_S):
                return [f"control replica {pid} never went live on "
                        f":{base_port + pid}\n{_tail(log)}"]
        if wait_alive(fport, [0, 1]) is None:
            problems.append(f"control: collector never saw both "
                            f"replicas alive\n{_tail(flog)}")
        t_end = time.monotonic() + 4.0
        while time.monotonic() < t_end and not problems:
            for pid in range(2):
                s, b = _serve_post(base_port + pid)
                if s != 200:
                    problems.append(f"control: replica {pid} answered "
                                    f"{s} ({b}) on clean traffic")
                    break
            time.sleep(0.1)
        time.sleep(1.0)  # a few more evaluation cycles on the tail
        stray = slo.load_incidents(ctl_rsl)
        if stray:
            problems.append(f"control: {len(stray)} incident(s) on "
                            f"CLEAN traffic, first slo: "
                            f"{stray[0].get('slo')}")
    finally:
        teardown(procs, coll, "control")
    if not os.path.exists(os.path.join(ctl_rsl, "fleet-metrics.jsonl")):
        problems.append("control: collector persisted no "
                        "fleet-metrics.jsonl")
    if problems:
        return problems
    print("chaos gate H: clean control — both replicas scraped, zero "
          "incidents")

    # -- fault world: burst -> one incident, rank loss -> age-out -----
    BURST_FAILS = 12
    plan_path = os.path.join(work, "fleet_plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": [
            {"site": "serve.infer", "kind": "ioerror", "after_n": 1,
             "count": BURST_FAILS, "rank": 1},
            {"site": "serve.infer", "kind": "rank_loss",
             "after_n": 1 + BURST_FAILS, "count": 1, "rank": 1},
        ]}, f)
    procs, coll, base_port, fport, flog = launch("fault", rsl,
                                                 plan_path)
    try:
        for pid, p, log in procs:
            if not _serve_wait_live(base_port + pid, p,
                                    SERVE_LIVE_WAIT_S):
                return [f"fault replica {pid} never went live on "
                        f":{base_port + pid}\n{_tail(log)}"]
        if wait_alive(fport, [0, 1]) is None:
            problems.append(f"fault: collector never saw both "
                            f"replicas alive\n{_tail(flog)}")
        # baseline clean traffic (the burn rate needs a denominator);
        # replica 1's first hit is clean — the burst starts at hit 2
        s, _ = _serve_post(base_port + 1)
        if s != 200:
            problems.append(f"fault: replica 1's pre-burst request "
                            f"answered {s}")
        for _ in range(8):
            _serve_post(base_port)
            time.sleep(0.1)
        # the burst: every replica-1 answer is the injected 500
        codes = [_serve_post(base_port + 1)[0]
                 for _ in range(BURST_FAILS)]
        if codes != [500] * BURST_FAILS:
            problems.append(f"fault: burst answered {codes}, expected "
                            f"{BURST_FAILS} injected 500s")
        # the SLO must fire and write its one bundle
        bundles, deadline = [], time.monotonic() + 30.0
        while time.monotonic() < deadline:
            bundles = slo.load_incidents(rsl)
            if bundles:
                break
            time.sleep(0.25)
        if not bundles:
            problems.append(f"fault: no incident bundle within 30s of "
                            f"the burst\n{_tail(flog)}")
        else:
            b = bundles[0]
            if b.get("slo") != "serve-errors":
                problems.append(f"fault: incident names slo "
                                f"{b.get('slo')!r}")
            if b.get("suspect_ranks") != [1]:
                problems.append(f"fault: incident suspects "
                                f"{b.get('suspect_ranks')}, expected "
                                f"[1]")
            offs = b.get("offending_requests") or []
            if not offs or not all(o.startswith("r1-") for o in offs):
                problems.append(f"fault: offending request ids wrong: "
                                f"{offs[:4]}")
        # exactly ONE bundle per episode: several more collector
        # cycles must not mint another
        time.sleep(2.0)
        n = len(slo.load_incidents(rsl))
        if n != 1:
            problems.append(f"fault: {n} incident bundles for one "
                            f"episode, expected exactly 1")
        # rank loss: the next replica-1 request dies with its socket
        try:
            s, b = _serve_post(base_port + 1, timeout=20.0)
            problems.append(f"fault: replica 1's rank-loss request "
                            f"ANSWERED ({s}, {b})")
        except OSError:
            pass
        procs[1][1].wait(timeout=60)
        # ...and the dead rank ages out of the fleet series
        doc = wait_alive(fport, [0])
        if doc is None:
            problems.append(f"fault: dead rank 1 never aged out of "
                            f"the fleet series\n{_tail(flog)}")
        elif "1" in (doc.get("targets") or {}):
            problems.append("fault: aged-out rank 1 still present in "
                            "the fleet targets")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fport}/metrics",
                    timeout=5) as r:
                text = r.read().decode()
            if not text.endswith("dpt_up 1\n"):
                problems.append(f"fault: stale dpt_up after the rank "
                                f"loss: ...{text[-40:]!r}")
        except OSError as e:
            problems.append(f"fault: fleet /metrics scrape after the "
                            f"rank loss failed: {e}")
        n = len(slo.load_incidents(rsl))
        if n != 1:
            problems.append(f"fault: rank loss minted extra incident "
                            f"bundles ({n} total, expected 1)")
    finally:
        teardown(procs, coll, "fault")
    if not problems:
        print(f"chaos gate H: {BURST_FAILS}-failure burst -> one "
              f"incident (rank 1, {BURST_FAILS} offender ids), rank "
              f"loss aged out of the fleet series")
    return problems


def _tail(path: str, n: int = 2500) -> str:
    try:
        return open(path).read()[-n:]
    except OSError:
        return "<no log>"


def child_main(a) -> int:
    """One multi-process rank (stages D and E): join the gloo
    rendezvous, train under the given plan/flags, exit CHILD_EXIT on
    the agreed failure path — and, if the world was reconfigured, leave
    through ``elastic.quiesce_exit`` (the parked pre-shrink runtime
    must never see interpreter teardown)."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import elastic, faults, runtime
    from distributedpytorch_tpu.cli import run_serve, run_train

    if not a.join:
        # A joiner never dials the old coordinator: run_train routes it
        # through the join-claim protocol (runtime.join_distributed).
        runtime.initialize_distributed(coordinator_address=a.coord,
                                       num_processes=a.nprocs,
                                       process_id=a.pid,
                                       elastic=a.elastic)
    if a.serve:
        # Stage G rank: one serving replica (run_serve finds the
        # runtime already initialized).  rc mirrors the train path.
        cfg = _base_cfg(a.rsl).replace(
            action="serve", checkpoint_file=a.ckpt, fault_plan=a.plan,
            elastic=a.elastic, serve_port=a.serve_port,
            serve_buckets="1,4", serve_max_latency_ms=10.0,
            serve_queue=16, health_timeout=20.0,
            metrics_port=a.metrics_port)
        try:
            run_serve(cfg)
        except (faults.FatalFaultError, faults.PeerFailureError) as e:
            print(f"rank {a.pid}: agreed fatal exit: {e}",
                  file=sys.stderr)
            rc = CHILD_EXIT
        else:
            rc = 0
            print(f"rank {a.pid}: serve drained, rc=0", file=sys.stderr)
        if elastic.reconfigured():
            elastic.quiesce_exit(rc)  # never returns
        return rc
    cfg = _base_cfg(a.rsl).replace(
        fault_plan=a.plan, nb_epochs=a.epochs, batch_size=4,
        checkpoint_file=a.ckpt, elastic=a.elastic or a.join,
        elastic_join=a.join,
        # stage F resumes from mid-run snapshots the driver picks after
        # the fact: keep every epoch's file out of rotation's reach
        keep_ckpts=a.epochs,
        health_timeout=20.0 if (a.elastic or a.join) else 0.0,
        # stages E/F stream: data.host_batch (the rank_loss site) is
        # only live on the streamed path, and reshard-on-shrink/grow is
        # the ShardedLoader contract under proof here
        data_mode="stream" if a.stream else "auto")
    try:
        run_train(cfg)
    except (faults.FatalFaultError, faults.PeerFailureError) as e:
        print(f"rank {a.pid}: agreed fatal exit: {e}", file=sys.stderr)
        rc = CHILD_EXIT
    else:
        rc = 0
        print(f"rank {a.pid}: run finished, rc=0", file=sys.stderr)
    if elastic.reconfigured():
        elastic.quiesce_exit(rc)  # never returns
    return rc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", choices=("core", "elastic", "grow",
                                        "serve", "fleet"),
                    default="core")
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--join", action="store_true")
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--serve-port", type=int, default=0,
                    dest="serve_port")
    ap.add_argument("--metrics-port", type=int, default=0,
                    dest="metrics_port")
    ap.add_argument("--coord")
    ap.add_argument("--pid", type=int)
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--plan")
    ap.add_argument("--rsl")
    ap.add_argument("--ckpt")
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--stream", action="store_true")
    args = ap.parse_args()
    sys.exit(child_main(args) if args.child else main(args.stage))
