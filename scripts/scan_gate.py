#!/usr/bin/env python3
"""--scan-layers regression gate leg (scripts/gate.sh).

The scan transform's contract is "invisible except for compile time",
and this leg re-proves each clause on every gate run:

  * numerics: scanned forward + gradients allclose to the unrolled
    loop after layout conversion, on BOTH deep-zoo extremes — vit
    (train mode; homogeneous transformer blocks) and densenet121
    (eval mode; the padded-buffer scan over 58 dense layers.  Eval
    pins BN to stored stats: train-mode equality holds too but only
    in f64 — 58 stacked batch-stat reductions amplify f32
    reduction-order noise chaotically, see tests/test_scan_layers.py);
  * checkpoints: bidirectional cross-layout restore through the CLI
    on the ORBAX path (meta.json params_layout -> abstract-target
    conversion; the msgpack path is tier-1's
    test_checkpoint_converts_across_scan_flag) — a --scan-layers-
    trained directory `test -f`s on a plain config and vice versa;
  * compile cost: the scanned densenet forward compiles to >= 3x
    fewer optimized-HLO instructions than the unrolled one (measured
    4.8x on CPU; 3x is the regression floor, not the claim).

CPU-only (the virtual test mesh), ~3 min — the densenet121 init and
grads dominate.
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

HLO_REDUCTION_FLOOR = 3.0
GRAD_TOL = 2e-4


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _grads_allclose(plain, sc, vp, vars_scan, x, back_layout, train,
                    problems, what):
    """Scale-aware gradient comparison (leaves whose true gradient is
    ~0 — conv bias under BN — carry only float noise; compare them on
    the leaf's own scale, not relative)."""
    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu
    import numpy as np
    from flax import serialization

    from distributedpytorch_tpu.models import scan

    def loss(mdl, variables, p):
        out = mdl.apply({**variables, "params": p}, x, train)
        if isinstance(out, tuple):
            out = out[0]
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda p: loss(plain, vp, p))(vp["params"])
    g2 = jax.grad(lambda p: loss(sc, vars_scan, p))(vars_scan["params"])
    g2c = scan.convert_layout(serialization.to_state_dict(g2),
                              back_layout)
    flat2 = {jtu.keystr(k): v
             for k, v in jtu.tree_flatten_with_path(g2c)[0]}
    flat1 = jtu.tree_flatten_with_path(serialization.to_state_dict(g1))[0]
    if set(jtu.keystr(k) for k, _ in flat1) != set(flat2):
        problems.append(f"{what}: converted grad tree != plain grad tree")
        return
    worst = 0.0
    for k, v in flat1:
        a, b = np.asarray(v), np.asarray(flat2[jtu.keystr(k)])
        scale = max(float(np.abs(a).max()), 1.0)
        diff = float(np.abs(b - a).max()) / scale
        worst = max(worst, diff)
        if diff > GRAD_TOL:
            problems.append(f"{what}: grad mismatch at {jtu.keystr(k)} "
                            f"(scaled diff {diff:.2e} > {GRAD_TOL})")
            return
    log(f"{what}: grads allclose (worst scaled diff {worst:.2e})")


def check_vit(problems) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import serialization

    from distributedpytorch_tpu.models import scan
    from distributedpytorch_tpu.models.vit import ViT

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
    plain = ViT(num_classes=10, dtype=jnp.float32)
    sc = ViT(num_classes=10, dtype=jnp.float32, scan_layers=True)
    vp = plain.init(rng, x, True)
    vars_scan = serialization.from_state_dict(
        sc.init(rng, x, True),
        scan.convert_layout(serialization.to_state_dict(vp), "scan"))
    fwd = float(np.abs(np.asarray(sc.apply(vars_scan, x, True))
                       - np.asarray(plain.apply(vp, x, True))).max())
    if fwd > 1e-5:
        problems.append(f"vit: scan forward diverges ({fwd:.2e})")
    _grads_allclose(plain, sc, vp, vars_scan, x, "blocks", True,
                    problems, "vit train-mode")


def check_densenet(problems) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import serialization

    from distributedpytorch_tpu import costs
    from distributedpytorch_tpu.models import scan
    from distributedpytorch_tpu.models.densenet import DenseNet

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    t0 = time.monotonic()
    plain = DenseNet(num_classes=10, dtype=jnp.float32)
    sc = DenseNet(num_classes=10, dtype=jnp.float32, scan_layers=True)
    vp = plain.init(rng, x, False)
    vs = sc.init(rng, x, False)
    sd = serialization.to_state_dict(
        {"params": vp["params"], "batch_stats": vp["batch_stats"]})
    vars_scan = serialization.from_state_dict(
        {"params": vs["params"], "batch_stats": vs["batch_stats"]},
        scan.convert_layout(sd, "dense_scan"))
    log(f"densenet121 init + layout convert: {time.monotonic() - t0:.1f}s")
    fwd = float(np.abs(np.asarray(sc.apply(vars_scan, x, False))
                       - np.asarray(plain.apply(vp, x, False))).max())
    if fwd > 1e-4:
        problems.append(f"densenet: scan forward diverges ({fwd:.2e})")
    _grads_allclose(plain, sc, vp, vars_scan, x, "dense_layers", False,
                    problems, "densenet eval-mode")

    # compile cost: the acceptance floor on the model the feature was
    # built for (58 stacked dense layers)
    counts = {}
    for name, mdl, variables in (("noscan", plain, vp),
                                 ("scan", sc, vars_scan)):
        compiled = jax.jit(
            lambda v, xx, m=mdl: m.apply(v, xx, False)
        ).lower(variables, x).compile()
        counts[name] = costs.hlo_instruction_count(compiled.as_text())
    ratio = counts["noscan"] / max(counts["scan"], 1)
    log(f"densenet HLO instructions: {counts['noscan']} unrolled vs "
        f"{counts['scan']} scanned ({ratio:.1f}x)")
    if ratio < HLO_REDUCTION_FLOOR:
        problems.append(
            f"densenet scan HLO reduction regressed: {ratio:.1f}x < "
            f"{HLO_REDUCTION_FLOOR}x floor ({counts})")


def check_orbax_checkpoint(problems) -> None:
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        log("orbax not installed — cross-layout orbax restore leg "
            "skipped (msgpack direction is covered in tier-1)")
        return

    import numpy as np

    from distributedpytorch_tpu.cli import run_test, run_train
    from distributedpytorch_tpu.config import Config

    losses = {}
    for train_scan in (True, False):
        rsl = tempfile.mkdtemp(prefix=f"scan_gate_ckpt{int(train_scan)}_")
        run_train(Config(
            action="train", data_path="/nodata", rsl_path=rsl,
            dataset="synthetic", model_name="vit", batch_size=8,
            nb_epochs=1, debug=True, half_precision=False,
            scan_layers=train_scan, ckpt_format="orbax"))
        ckpt = f"{rsl}/bestmodel-synthetic-vit.ckpt"
        if not os.path.isdir(ckpt):
            problems.append(f"orbax checkpoint dir missing: {ckpt}")
            return
        # restore under the OPPOSITE layout: the gate's whole point
        res = run_test(Config(
            action="test", data_path="/nodata", rsl_path=rsl,
            dataset="synthetic", debug=True, half_precision=False,
            checkpoint_file=ckpt, scan_layers=not train_scan))
        direction = ("scan->blocks" if train_scan else "blocks->scan")
        if res["model_name"] != "vit" \
                or not np.isfinite(res["test_loss"]):
            problems.append(f"orbax cross-layout restore broken "
                            f"({direction}): {res}")
            return
        losses[direction] = res["test_loss"]
        log(f"orbax {direction} restore OK (test loss "
            f"{res['test_loss']:.4f})")


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    problems = []
    check_vit(problems)
    check_densenet(problems)
    check_orbax_checkpoint(problems)

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("scan gate OK: vit + densenet grads allclose, cross-layout "
          "restore, HLO reduction above floor")
    return 0


if __name__ == "__main__":
    sys.exit(main())
