#!/usr/bin/env python3
"""Goodput ledger + live /metrics gate leg (scripts/gate.sh), on CPU.

Three stages, all bounded:

  A. attribution under injected badput — a 2-epoch synthetic run under
     a canned plan combining a 0.5 s ``data.host_batch`` stall with two
     transient ``ckpt.save`` I/O errors, with the live exporter on.
     The ledger (RSL/goodput.json) must account >= 99% of wall clock
     (the residual is an explicit category, never hidden), land the
     stall in data_wait, and land the retry sleeps in retry_backoff —
     WITHOUT the enclosing ckpt_blocking window double-counting them.
     While the run is alive a scraper thread polls the exporter:
     /metrics must parse as Prometheus text carrying the goodput
     counters, /healthz as JSON naming the rank.
  B. artifact surfaces — ``main.py goodput`` summarizes the run and
     names the top badput cause; ``main.py timeline`` on the same dir
     carries the per-rank goodput category track.
  C. exporter overhead budget — min-of-2 timed runs with --metrics-port
     on (under continuous scraping) vs off (same run dir per variant so
     run 2 hits the compile cache) must stay within 2% (+0.6 s absolute
     floor for scheduler noise on these short CPU runs).

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/goodput_gate.py``.
"""

import json
import os
import socket
import sys
import tempfile
import threading
import time
import subprocess
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OVERHEAD_REL = 0.02     # exporter-on budget vs exporter-off
OVERHEAD_ABS_S = 0.6    # noise floor for short CPU runs
RESIDUAL_MAX = 0.01     # ledger must attribute >= 99% of wall clock

# One 0.5 s stall late in epoch 0 (lands in the driver's inter-step
# wait window -> data_wait) plus two transient ckpt.save I/O errors
# (the sync saver's RetryPolicy sleeps on the driver -> retry_backoff,
# nested inside ckpt_blocking exactly once).
PLAN = "data.host_batch:stall:12:1:0.5;ckpt.save:ioerror:0:2"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _base_cfg(rsl: str, **overrides):
    from distributedpytorch_tpu.config import Config

    return Config(action="train", data_path="/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="mlp", batch_size=8,
                  nb_epochs=2, debug=True, half_precision=False,
                  telemetry=True, data_mode="stream", producer_threads=1,
                  ckpt_async=False, aot_warmup=True).replace(**overrides)


class _Scraper(threading.Thread):
    """Polls the live exporter while the run owns the main thread."""

    def __init__(self, port: int):
        super().__init__(name="gate-scraper", daemon=True)
        self.port = port
        self.stop = threading.Event()
        self.metrics_ok = 0
        self.last_metrics = ""
        self.last_health = None

    def run(self):
        while not self.stop.is_set():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{self.port}/metrics",
                        timeout=2) as r:
                    body = r.read().decode("utf-8")
                if "dpt_up 1" in body:
                    self.metrics_ok += 1
                    self.last_metrics = body
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{self.port}/healthz",
                        timeout=2) as r:
                    self.last_health = json.loads(r.read().decode("utf-8"))
            except (OSError, ValueError):
                pass  # exporter not up yet / already down
            self.stop.wait(0.2)


def _prom_text_valid(body: str) -> bool:
    """Every non-comment line must be "name[{labels}] value"."""
    for line in body.strip().splitlines():
        if line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            return False
        try:
            float(parts[1])
        except ValueError:
            return False
    return True


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    from distributedpytorch_tpu.cli import run_train

    problems = []
    work = tempfile.mkdtemp(prefix="goodput_gate_")

    # -- stage A: attribution under injected badput, scraped live -----
    rsl_a = os.path.join(work, "badput")
    port = _free_port()
    scraper = _Scraper(port)
    scraper.start()
    run_train(_base_cfg(rsl_a, fault_plan=PLAN, metrics_port=port))
    scraper.stop.set()
    scraper.join(timeout=10)

    if scraper.metrics_ok == 0:
        problems.append("no successful /metrics scrape during the run — "
                        "the exporter never served")
    else:
        body = scraper.last_metrics
        if not _prom_text_valid(body):
            problems.append("/metrics body is not valid Prometheus "
                            "text exposition")
        for needle in ("dpt_goodput_seconds_total{category=\"compute\"}",
                       "dpt_step_dispatch_s{quantile=\"0.5\"}",
                       "dpt_up 1"):
            if needle not in body:
                problems.append(f"/metrics is missing {needle!r}")
    health = scraper.last_health
    if not health or health.get("rank") != 0 \
            or health.get("status") != "ok":
        problems.append(f"/healthz unusable during the run: {health}")

    try:
        with open(os.path.join(rsl_a, "goodput.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        problems.append(f"no readable goodput.json ({e})")
        doc = None
    if doc:
        wall, cats = doc["wall_s"], doc["categories"]
        if doc["accounted_s"] < (1.0 - RESIDUAL_MAX) * wall:
            problems.append(
                f"ledger accounts {doc['accounted_s']:.2f}s of "
                f"{wall:.2f}s wall — more than {RESIDUAL_MAX:.0%} "
                f"leaked past the category hooks")
        for row in doc["epochs"]:
            got = sum(row["categories"].values())
            if abs(got - row["wall_s"]) > max(0.01 * row["wall_s"], 1e-3):
                problems.append(
                    f"epoch {row['epoch']} row sums to {got:.3f}s vs "
                    f"window {row['wall_s']:.3f}s — reconcile broke "
                    f"the sums-to-wall invariant")
        if cats.get("data_wait", 0.0) < 0.4:
            problems.append(
                f"data_wait={cats.get('data_wait', 0.0):.3f}s — the "
                f"injected 0.5s stall was not attributed to data_wait")
        if cats.get("retry_backoff", 0.0) < 0.02:
            problems.append(
                f"retry_backoff={cats.get('retry_backoff', 0.0):.3f}s "
                f"— the ckpt.save retry sleeps were not attributed")
        if cats.get("compute", 0.0) <= 0.0:
            problems.append("compute category is empty — the step loop "
                            "hook is not wired")
        # non-overlap spot check: nothing exceeds wall clock
        if sum(cats.values()) > wall * 1.01:
            problems.append(
                f"categories sum to {sum(cats.values()):.2f}s over "
                f"{wall:.2f}s wall — something is double-counted")
        print(f"goodput gate A: wall {wall:.2f}s, residual "
              f"{100 * doc['residual_frac']:.2f}%, data_wait "
              f"{cats.get('data_wait', 0):.2f}s, retry_backoff "
              f"{cats.get('retry_backoff', 0):.3f}s, "
              f"{scraper.metrics_ok} live scrape(s)")

    # -- stage B: the offline artifact surfaces -----------------------
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rep = subprocess.run([sys.executable, "main.py", "goodput",
                          "--rsl_path", rsl_a], cwd=REPO, env=env,
                         capture_output=True, text=True)
    if rep.returncode != 0 or "top badput cause" not in rep.stdout:
        problems.append(f"main.py goodput rc={rep.returncode}; output "
                        f"missing the top-badput callout:\n"
                        f"{rep.stdout[-800:]}\n{rep.stderr[-800:]}")
    tl = subprocess.run([sys.executable, "main.py", "timeline",
                         "--rsl_path", rsl_a], cwd=REPO, env=env,
                        capture_output=True, text=True)
    if tl.returncode != 0:
        problems.append(f"main.py timeline rc={tl.returncode}:\n"
                        f"{tl.stdout[-800:]}\n{tl.stderr[-800:]}")
    else:
        with open(os.path.join(rsl_a, "timeline.json")) as f:
            trace = json.load(f)
        gp_events = [e for e in trace["traceEvents"]
                     if e.get("cat") == "goodput"]
        if not any(e["ph"] == "X" for e in gp_events) \
                or not any(e["ph"] == "C" for e in gp_events):
            problems.append(
                f"timeline has {len(gp_events)} goodput event(s) — "
                f"expected both category slices (X) and the stacked "
                f"counter series (C)")
        else:
            print(f"goodput gate B: summary + timeline track "
                  f"({len(gp_events)} goodput trace events)")

    # -- stage C: exporter overhead budget ----------------------------
    def timed(rsl: str, metrics_port: int) -> float:
        best = float("inf")
        for _ in range(2):  # same rsl: run 2 reuses the compile cache
            scr = _Scraper(metrics_port) if metrics_port else None
            if scr:
                scr.start()
            t0 = time.perf_counter()
            run_train(_base_cfg(rsl, metrics_port=metrics_port))
            best = min(best, time.perf_counter() - t0)
            if scr:
                scr.stop.set()
                scr.join(timeout=10)
        return best

    t_off = timed(os.path.join(work, "exp_off"), 0)
    t_on = timed(os.path.join(work, "exp_on"), _free_port())
    budget = t_off * (1.0 + OVERHEAD_REL) + OVERHEAD_ABS_S
    if t_on > budget:
        problems.append(
            f"exporter overhead: on={t_on:.2f}s vs off={t_off:.2f}s "
            f"exceeds the {OVERHEAD_REL:.0%}+{OVERHEAD_ABS_S}s budget "
            f"({budget:.2f}s) — live monitoring is too expensive")
    print(f"goodput gate C: exporter on={t_on:.2f}s off={t_off:.2f}s "
          f"(budget {budget:.2f}s)")

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("goodput gate OK: ledger sums to wall, badput attributed, "
          "live /metrics served, overhead within budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
