#!/usr/bin/env python3
"""graftlint — JAX/TPU-aware static analysis for this repo.

Static pass (default): the framework rule catalog in
distributedpytorch_tpu/analysis/rules.py — per-file rules plus the
whole-program analyses (collective-divergence, lock-order-cycle,
mesh-axis-propagation) — over the package, entry points, bench harness
and scripts.  Exit 0 = clean, 1 = findings.

    python scripts/graftlint.py            # human output
    python scripts/graftlint.py --json     # machine-readable
    python scripts/graftlint.py FILE...    # focused run
    python scripts/graftlint.py --changed-only [--base REF]
                                           # findings only in files git
                                           # sees as changed; the whole
                                           # program is still analyzed
                                           # (whole-repo is the gate
                                           # default)
    python main.py lint                    # equivalent in-CLI form

Runtime sanitizer:

    python scripts/graftlint.py --smoke    # 1-epoch CPU train under
                                           # jax.transfer_guard; fails
                                           # on silent device->host
                                           # transfers

Suppressions: ``# graftlint: disable=<rule> -- <rationale>`` (rationale
required).  See README "Static analysis & sanitizers".
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the repo scope)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings output")
    p.add_argument("--smoke", action="store_true",
                   help="run the transfer-guard runtime smoke instead "
                        "of the static pass (forces JAX_PLATFORMS=cpu)")
    p.add_argument("--changed-only", action="store_true",
                   help="report findings only in git-changed files "
                        "(whole program still loaded, so "
                        "interprocedural rules stay sound)")
    p.add_argument("--base", default=None, metavar="REF",
                   help="with --changed-only: also include files "
                        "changed since REF (git diff REF...HEAD)")
    args = p.parse_args()
    if args.smoke:
        from distributedpytorch_tpu.analysis.transfer_guard import \
            main as smoke_main
        return smoke_main()
    from distributedpytorch_tpu.analysis.core import run_cli

    return run_cli(json_output=args.json, paths=args.paths or None,
                   root=_REPO_ROOT, changed_only=args.changed_only,
                   base=args.base)


if __name__ == "__main__":
    sys.exit(main())
