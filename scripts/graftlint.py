#!/usr/bin/env python3
"""graftlint — JAX/TPU-aware static analysis for this repo.

Static pass (default): the eight framework rules in
distributedpytorch_tpu/analysis/rules.py over the package, entry
points, bench harness and scripts.  Exit 0 = clean, 1 = findings.

    python scripts/graftlint.py            # human output
    python scripts/graftlint.py --json     # machine-readable
    python scripts/graftlint.py FILE...    # focused run
    python main.py lint                    # equivalent in-CLI form

Runtime sanitizer:

    python scripts/graftlint.py --smoke    # 1-epoch CPU train under
                                           # jax.transfer_guard; fails
                                           # on silent device->host
                                           # transfers

Suppressions: ``# graftlint: disable=<rule> -- <rationale>`` (rationale
required).  See README "Static analysis & sanitizers".
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the repo scope)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings output")
    p.add_argument("--smoke", action="store_true",
                   help="run the transfer-guard runtime smoke instead "
                        "of the static pass (forces JAX_PLATFORMS=cpu)")
    args = p.parse_args()
    if args.smoke:
        from distributedpytorch_tpu.analysis.transfer_guard import \
            main as smoke_main
        return smoke_main()
    from distributedpytorch_tpu.analysis.core import run_cli

    return run_cli(json_output=args.json, paths=args.paths or None,
                   root=_REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
