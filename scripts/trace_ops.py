#!/usr/bin/env python3
"""Op-level time attribution for the headline train step.

Captures a jax.profiler device trace of the resident cnn/b64 epoch program
and aggregates device-op durations by HLO op name from the Chrome-trace
JSON the profiler writes — no tensorboard needed.  Prints the top ops by
total device time.  Companion to scripts/profile_breakdown.py (stage-level
deltas); this one answers "which HLO inside the step".

Usage: python scripts/trace_ops.py [--steps 400] [--batch 64] [--top 40]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--model", default="cnn")
    p.add_argument("--top", type=int, default=40)
    args = p.parse_args()

    import jax

    from bench import _make_corpus
    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.data.pipeline import ResidentLoader
    from distributedpytorch_tpu.models import get_model, get_model_input_size
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh()
    dataset = _make_corpus(28, 1, 60000)
    loader = ResidentLoader(dataset.splits["train"], mesh, args.batch,
                            shuffle=True, seed=1234)
    model = get_model(args.model, dataset.nb_classes)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, len(loader), False)
    engine = Engine(model, args.model, get_loss_fn("cross_entropy"), tx,
                    dataset.mean, dataset.std,
                    get_model_input_size(args.model))
    state = jax.device_put(
        engine.init_state(utils.root_key(1234)),
        runtime.replicated_sharding(mesh))
    key = utils.root_key(1234)
    idx, valid = loader.epoch_plan(0)
    idx, valid = idx[:args.steps], valid[:args.steps]

    compiled = engine.train_epoch.lower(
        state, loader.images, loader.labels, idx, valid, key).compile()
    st, m = compiled(state, loader.images, loader.labels, idx, valid, key)
    jax.block_until_ready(m["loss"])  # warmup outside the trace

    tmpdir = tempfile.mkdtemp(prefix="dpt_trace_")
    jax.profiler.start_trace(tmpdir)
    try:
        st, m = compiled(st, loader.images, loader.labels, idx, valid,
                         key)
        jax.block_until_ready(m["loss"])
    finally:
        # a raised dispatch must not leak a running global profiler
        jax.profiler.stop_trace()

    files = glob.glob(os.path.join(
        tmpdir, "**", "*.trace.json.gz"), recursive=True)
    if not files:
        log(f"no trace json found under {tmpdir}")
        return 1

    # Aggregate complete events from device lanes (pid names with 'TPU' /
    # 'Chip'/'device'), skipping host python threads.
    by_op = collections.Counter()
    total = 0.0
    for path in files:
        with gzip.open(path, "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
        pid_names = {e["pid"]: e["args"].get("name", "")
                     for e in events
                     if e.get("ph") == "M" and e.get("name") == "process_name"
                     and "args" in e}
        device_pids = {pid for pid, name in pid_names.items()
                       if re.search(r"(tpu|chip|device|/device:)",
                                    name, re.I)
                       and not re.search(r"host", name, re.I)}
        for e in events:
            if e.get("ph") != "X" or e.get("pid") not in device_pids:
                continue
            dur = float(e.get("dur", 0.0))
            name = e.get("name", "?")
            by_op[name] += dur
            total += dur
    if not by_op:
        log("no device events matched; pid names were: "
            + ", ".join(sorted(set(pid_names.values()))))
        return 1

    n = args.steps
    log(f"device op time over {n} steps (us/step), total "
        f"{total / n:.1f} us/step:")
    rows = []
    for name, dur in by_op.most_common(args.top):
        rows.append({"op": name, "us_per_step": round(dur / n, 2),
                     "pct": round(100 * dur / total, 1)})
        log(f"  {dur / n:8.2f} us  {100 * dur / total:5.1f}%  {name[:90]}")
    print(json.dumps({"total_us_per_step": round(total / n, 2),
                      "top": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
