#!/usr/bin/env python3
"""Anomaly/flight-recorder gate leg (scripts/gate.sh): the black box and
the anomaly profiler, end to end, on CPU.

Four stages, all bounded:

  A. deterministic trigger — a 2-epoch synthetic run under a canned
     ``data.host_batch:stall`` fault plan with --anomaly-capture: the
     single injected stall must produce >=1 ``anomaly`` telemetry event,
     EXACTLY one programmatic profiler capture directory (with real
     profile output in it — start_trace AND stop_trace both ran), and a
     ``flightrec-rank0.json`` dump whose ring carries the anomaly.
  B. clean control — the same run with NO fault plan: zero anomaly
     events and zero captures (the detector's thresholds must not fire
     on the run's own jitter), while the flight recorder still dumps at
     run end.
  C. overhead budget — the recorder is on by default, so it must be
     near-free: min-of-2 timed runs with the recorder ON vs OFF (same
     run dir per variant, so run 2 hits the persistent compile cache and
     the minimum measures steady state) must stay within 3% (+0.75 s
     absolute floor for scheduler noise on these ~10 s CPU runs).
  D. 2-rank timeline — two real processes (gloo rendezvous) share one
     run dir; ``main.py timeline`` on it must emit valid Chrome
     trace-event JSON with both ranks, per-rank monotonic event order,
     health-boundary clock alignment and a cross-rank skew summary.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python scripts/anomaly_gate.py``.
The script re-execs itself with ``--child`` for stage D's ranks.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OVERHEAD_REL = 0.03     # recorder-on budget vs recorder-off
OVERHEAD_ABS_S = 0.75   # noise floor for short CPU runs
CHILD_DEADLINE_S = 420.0

# One stall late in epoch 0 (25 steps/epoch at batch 8 over the 200
# synthetic examples): the detector's window (8) is full and the 0.5 s
# sleep dwarfs every threshold — fires deterministically, exactly once.
STALL_PLAN = "data.host_batch:stall:12:1:0.5"


def _events(rsl: str, rank: int = 0) -> list:
    path = os.path.join(rsl, "telemetry", f"rank{rank}.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _named(events: list, name: str) -> list:
    return [e for e in events
            if e.get("kind") == "event" and e.get("name") == name]


def _base_cfg(rsl: str, **overrides):
    from distributedpytorch_tpu.config import Config

    return Config(action="train", data_path="/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="mlp", batch_size=8,
                  nb_epochs=2, debug=True, half_precision=False,
                  telemetry=True, data_mode="stream", producer_threads=1,
                  ckpt_async=True, aot_warmup=True).replace(**overrides)


def _capture_dirs(rsl: str) -> list:
    d = os.path.join(rsl, "anomaly_traces")
    if not os.path.isdir(d):
        return []
    return sorted(n for n in os.listdir(d) if n.startswith("capture-"))


def _capture_has_profile(rsl: str, name: str) -> bool:
    for _, _, files in os.walk(os.path.join(rsl, "anomaly_traces", name)):
        if files:
            return True
    return False


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    from distributedpytorch_tpu.cli import run_train

    problems = []
    work = tempfile.mkdtemp(prefix="anomaly_gate_")
    anomaly_knobs = dict(anomaly_capture=True, anomaly_window=8,
                         anomaly_capture_steps=2, anomaly_max_captures=1)

    # -- stage A: deterministic trigger -------------------------------
    rsl_a = os.path.join(work, "stall")
    run_train(_base_cfg(rsl_a, fault_plan=STALL_PLAN, **anomaly_knobs))
    ev = _events(rsl_a)
    anomalies = _named(ev, "anomaly")
    if not anomalies:
        problems.append("stall run produced no anomaly telemetry event "
                        "— the detector missed the injected 0.5s stall")
    caps = _capture_dirs(rsl_a)
    if len(caps) != 1:
        problems.append(f"stall run produced {len(caps)} capture dirs "
                        f"{caps}, expected exactly one")
    elif not _capture_has_profile(rsl_a, caps[0]):
        problems.append(f"capture dir {caps[0]} is empty — stop_trace "
                        f"never flushed the programmatic profile")
    fr_path = os.path.join(rsl_a, "flightrec-rank0.json")
    try:
        with open(fr_path) as f:
            fr = json.load(f)
        ring_anoms = [r for r in fr["records"]
                      if r.get("kind") == "event"
                      and r.get("name") == "anomaly"]
        if not ring_anoms:
            problems.append("flight-record ring has no anomaly event — "
                            "recorder and detector are not wired "
                            "together")
        if "run_end" not in fr.get("reasons", []):
            problems.append(f"flight record reasons {fr.get('reasons')} "
                            f"missing run_end")
    except (OSError, ValueError, KeyError) as e:
        problems.append(f"no readable flight record at {fr_path} ({e})")
    print(f"anomaly gate A: {len(anomalies)} anomaly event(s) "
          f"({anomalies[0]['attrs']['trigger'] if anomalies else '-'}), "
          f"{len(caps)} capture(s), flight record dumped")

    # -- stage B: clean control (no false positives) ------------------
    rsl_b = os.path.join(work, "clean")
    run_train(_base_cfg(rsl_b, **anomaly_knobs))
    ev = _events(rsl_b)
    false_pos = _named(ev, "anomaly")
    if false_pos:
        problems.append(
            f"clean run fired {len(false_pos)} anomaly event(s) "
            f"({sorted(e['attrs'].get('trigger') for e in false_pos)}) — "
            f"thresholds trigger on the run's own jitter")
    caps = _capture_dirs(rsl_b)
    if caps:
        problems.append(f"clean run started capture(s) {caps} — "
                        f"captures without anomalies")
    if not os.path.exists(os.path.join(rsl_b, "flightrec-rank0.json")):
        problems.append("clean run left no flight-record dump at "
                        "run end")
    print(f"anomaly gate B: clean run — {len(false_pos)} anomalies, "
          f"{len(caps)} captures (both must be 0)")

    # -- stage C: recorder overhead budget ----------------------------
    def timed(rsl: str, flightrec: bool) -> float:
        best = float("inf")
        for _ in range(2):  # same rsl: run 2 reuses the compile cache
            t0 = time.perf_counter()
            run_train(_base_cfg(rsl, flightrec=flightrec))
            best = min(best, time.perf_counter() - t0)
        return best

    t_off = timed(os.path.join(work, "rec_off"), flightrec=False)
    t_on = timed(os.path.join(work, "rec_on"), flightrec=True)
    budget = t_off * (1.0 + OVERHEAD_REL) + OVERHEAD_ABS_S
    if t_on > budget:
        problems.append(
            f"recorder overhead: on={t_on:.2f}s vs off={t_off:.2f}s "
            f"exceeds the {OVERHEAD_REL:.0%}+{OVERHEAD_ABS_S}s budget "
            f"({budget:.2f}s) — the default-on recorder is too "
            f"expensive")
    print(f"anomaly gate C: recorder on={t_on:.2f}s off={t_off:.2f}s "
          f"(budget {budget:.2f}s)")

    # -- stage D: 2-rank gloo run -> timeline -------------------------
    problems += _stage_timeline(work)

    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        return 1
    print("anomaly gate OK: deterministic trigger, clean control, "
          "overhead budget and 2-rank timeline all green")
    return 0


def _stage_timeline(work: str) -> list:
    """Two real ranks (gloo) share one run dir; the timeline CLI must
    merge them into valid Chrome trace JSON with a skew summary."""
    import socket

    problems = []
    rsl = os.path.join(work, "tworank")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = [], []
    for pid in range(2):
        log = os.path.join(work, f"tworank{pid}.log")
        logs.append(log)
        # A log FILE, never a pipe: an undrained pipe backpressures a
        # chatty child into blocking mid-collective.
        out = open(log, "ab")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--coord", coord, "--pid", str(pid), "--rsl", rsl],
            cwd=REPO, env=env, stdout=out, stderr=out))
    deadline = time.monotonic() + CHILD_DEADLINE_S
    for pid, p in enumerate(procs):
        try:
            rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            problems.append(f"timeline rank {pid} HUNG past "
                            f"{CHILD_DEADLINE_S:.0f}s\n{_tail(logs[pid])}")
            continue
        if rc != 0:
            problems.append(f"timeline rank {pid} exited rc={rc}"
                            f"\n{_tail(logs[pid])}")
    if problems:
        return problems

    # The merger runs exactly as a user would run it.
    merged = subprocess.run(
        [sys.executable, "main.py", "timeline", "--rsl_path", rsl],
        cwd=REPO, env={**env, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True)
    if merged.returncode != 0:
        return [f"main.py timeline failed rc={merged.returncode}:\n"
                f"{merged.stdout[-1500:]}\n{merged.stderr[-1500:]}"]
    try:
        with open(os.path.join(rsl, "timeline.json")) as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        return [f"timeline.json unreadable ({e})"]
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        problems.append("timeline.json has no traceEvents")
        return problems
    pids = {e.get("pid") for e in evs}
    if not {0, 1} <= pids:
        problems.append(f"timeline covers pids {sorted(pids)}, "
                        f"expected both ranks 0 and 1")
    for pid in (0, 1):
        ts = [e["ts"] for e in evs
              if e.get("pid") == pid and e.get("ph") != "M"]
        if ts != sorted(ts):
            problems.append(f"rank {pid} trace events are not in "
                            f"monotonic ts order")
        if any(t < 0 for t in ts):
            problems.append(f"rank {pid} has negative trace timestamps")
    other = trace.get("otherData", {})
    if other.get("alignment") != "health_boundary":
        problems.append(f"2-rank run aligned via "
                        f"{other.get('alignment')!r}, expected "
                        f"'health_boundary'")
    if other.get("skew", {}).get("max_wall_skew_s") is None:
        problems.append("no cross-rank skew summary in the trace "
                        "(otherData.skew.max_wall_skew_s is null)")
    if "skew" not in merged.stdout:
        problems.append("timeline CLI summary does not mention skew")
    if not problems:
        print(f"anomaly gate D: 2-rank timeline valid "
              f"({len(evs)} trace events, max skew "
              f"{other['skew']['max_wall_skew_s']}s)")
    return problems


def _tail(path: str, n: int = 2500) -> str:
    try:
        return open(path).read()[-n:]
    except OSError:
        return "<no log>"


def child_main(a) -> int:
    """One stage-D rank: join the gloo rendezvous and run a short clean
    training into the SHARED run dir."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import runtime
    from distributedpytorch_tpu.cli import run_train

    runtime.initialize_distributed(coordinator_address=a.coord,
                                   num_processes=2, process_id=a.pid)
    run_train(_base_cfg(a.rsl, batch_size=4, producer_threads=0,
                        ckpt_async=False))
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--coord")
    ap.add_argument("--pid", type=int)
    ap.add_argument("--rsl")
    args = ap.parse_args()
    sys.exit(child_main(args) if args.child else main())
