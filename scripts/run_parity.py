#!/usr/bin/env python3
"""Run the full accuracy-parity suite and assemble PARITY.json.

5 Adam seeds (the round-3 protocol, re-pinned on the current tree) plus
one SGD+StepLR seed-pair (ref classif.py:122-131's second optimizer
path) — VERDICT r5 item 4.  Each run shells out to accuracy_parity.py
so ours and the reference see identical corpora per seed.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEEDS = [1234, 7, 99, 41, 2024]
SGD_SEEDS = [1234]
# Environment knobs for slow hosts (the round-6 container runs XLA-CPU at
# ~1/15th the round-5 machine's rate on one core; the resident epoch-scan
# is pathological there — see accuracy_parity.py --data-mode):
#   DPT_PARITY_TIMEOUT    per-run subprocess timeout, seconds (default 1500)
#   DPT_PARITY_DATA_MODE  ours-side data mode: auto|stream|resident
RUN_TIMEOUT = int(os.environ.get("DPT_PARITY_TIMEOUT", "1500"))
DATA_MODE = os.environ.get("DPT_PARITY_DATA_MODE", "auto")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


import functools


@functools.lru_cache(maxsize=1)
def _tree_rev() -> str:
    """Content hash of the files that determine parity numbers (the
    package + the parity harness) — part of the cache key so results
    from an older numerics tree never masquerade as current evidence,
    while doc/bench-only commits keep the cache valid.  Computed once
    per process (also keeps one suite run in one cache namespace even
    if a file is edited mid-run)."""
    import glob
    import hashlib

    h = hashlib.sha256()
    files = sorted(glob.glob(os.path.join(
        REPO, "distributedpytorch_tpu", "**", "*.py"), recursive=True))
    files.append(os.path.join(REPO, "scripts", "accuracy_parity.py"))
    for path in files:
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:10]


def one(seed: int, optimizer: str, ref_init: str = "torch",
        skip_ours: bool = False) -> dict:
    # Per-run cache: a crashed/interrupted suite re-run reuses finished
    # seeds instead of re-paying ~7 min each (delete /tmp/parity_cache_*
    # to force).  Keyed by the numerics-tree content hash + full run
    # config (doc-only commits deliberately keep entries valid).
    tag = f"{_tree_rev()}_{optimizer}_{seed}" \
        + ("" if ref_init == "torch" else f"_{ref_init}") \
        + ("_refonly" if skip_ours else "") \
        + ("" if DATA_MODE == "auto" else f"_{DATA_MODE}")
    cache = f"/tmp/parity_cache_{tag}.json"
    if os.path.exists(cache):
        log(f"=== parity seed {seed} optimizer {optimizer} (cached) ===")
        with open(cache) as f:
            return json.load(f)
    cmd = [sys.executable, os.path.join(REPO, "scripts",
                                        "accuracy_parity.py"),
           "--dataset", "synthetic_hard", "--seed", str(seed),
           "--optimizer", optimizer, "--ref-init", ref_init,
           "--rsl", f"/tmp/parity_rsl_{tag}",
           "--data-mode", DATA_MODE]
    if skip_ours:
        cmd.append("--skip-ours")
    log(f"=== parity seed {seed} optimizer {optimizer} "
        f"init {ref_init} (data-mode {DATA_MODE}, "
        f"timeout {RUN_TIMEOUT}s) ===")
    # Normal runs take ~7-8 min; a hung TPU tunnel (backend init that
    # neither errors nor returns) would otherwise pin the whole suite.
    res = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                         timeout=RUN_TIMEOUT)
    if res.returncode != 0:
        log(res.stderr[-4000:])
        raise RuntimeError(f"parity run failed (seed {seed})")
    out = json.loads(res.stdout.strip().splitlines()[-1])
    with open(cache, "w") as f:
        json.dump(out, f)
    return out


def _tolerant(label: str, failures: list, fn, *args, **kwargs):
    """A failure in one run (hung TPU tunnel, crashed subprocess, bad
    stdout) must not discard the other runs' finished evidence: record
    what happened — VERBATIM, so a genuine numerics crash is auditable
    and cannot hide behind an 'environment' label — and continue."""
    try:
        return fn(*args, **kwargs)
    except (RuntimeError, subprocess.TimeoutExpired,
            ValueError, IndexError) as e:  # JSONDecodeError is a ValueError
        log(f"{label} FAILED ({type(e).__name__}: {e}); continuing")
        failures.append({"run": label,
                         "error": f"{type(e).__name__}: {e}"[:300]})
        return None


def main() -> int:
    failed: list = []
    runs = [r for s in SEEDS
            if (r := _tolerant(f"adam_{s}", failed, one, s, "adam"))]
    if len(runs) < 2:
        raise RuntimeError("fewer than 2 adam seeds completed")
    sgd_runs = [r for s in SGD_SEEDS
                if (r := _tolerant(f"sgd_{s}", failed, one, s, "sgd"))]
    # Init CONTROL for the SGD pair: the reference with torch-default
    # init (kaiming-uniform(a=sqrt(5)) + uniform biases) stays at chance
    # under SGD(1e-3)+StepLR(0.1/epoch) — saturated logits give SGD no
    # usable gradient where adam's per-param rescaling escapes.  The
    # same torch loop with flax-style init (lecun-normal, zero biases)
    # isolates the effect: if it matches ours, the SGD learning-dynamics
    # paths agree and the residual is init policy, not optimizer math.
    sgd_controls = [r for s in SGD_SEEDS
                    if (r := _tolerant(f"sgd_{s}_lecun_control", failed,
                                       one, s, "sgd", ref_init="lecun",
                                       skip_ours=True))]

    ours = [r["ours"]["test_acc"] for r in runs]
    ref = [r["reference"]["test_acc"] for r in runs]
    deltas = [round((o - r) * 100, 2) for o, r in zip(ours, ref)]
    out = {
        "round": 6,
        "corpus": "synthetic_hard (data/io.py SYNTH_HARD: class_sep 0.45,"
                  " noise 70)",
        "protocol": "2 epochs, batch 64, best-valid-loss model both "
                    "sides, identical corpus/split per seed",
        "precision_policy": "bf16 (the default: f32 master params, "
                            "bfloat16 compute, f32 accumulation — the "
                            "same dtypes every earlier round ran "
                            "implicitly, now named and telemetry-"
                            "recorded)",
        "data_mode": DATA_MODE,
        "n_seeds": len(runs),
        "seeds": [r["seed"] for r in runs],
        "runs_failed": failed,
        "ours_test_acc": ours,
        "reference_test_acc": ref,
        "deltas_pp": deltas,
        "mean_ours": round(statistics.mean(ours) * 100, 2),
        "mean_reference": round(statistics.mean(ref) * 100, 2),
        "mean_delta_pp": round(statistics.mean(deltas), 2),
        "sd_delta_pp": round(statistics.stdev(deltas), 2),
        "sd_ours_pp": round(statistics.stdev(ours) * 100, 2),
        "sd_reference_pp": round(statistics.stdev(ref) * 100, 2),
        "sgd": [{
            "seed": r["seed"],
            "ours_test_acc": r["ours"]["test_acc"],
            "reference_test_acc": r["reference"]["test_acc"],
            "reference_lecun_init_test_acc": c["reference"]["test_acc"],
            "delta_vs_torch_default_pp": round(
                (r["ours"]["test_acc"]
                 - r["reference"]["test_acc"]) * 100, 2),
            "delta_vs_init_control_pp": round(
                (r["ours"]["test_acc"]
                 - c["reference"]["test_acc"]) * 100, 2),
        } for r, c in zip(sgd_runs, sgd_controls)],
        "runs": runs + sgd_runs + sgd_controls,
    }
    adam_ok = abs(out["mean_delta_pp"]) <= 2 * out["sd_delta_pp"]
    if out["sgd"]:
        sgd = out["sgd"][0]
        ref_at_chance = sgd["reference_test_acc"] < 0.25
        control_close = abs(sgd["delta_vs_init_control_pp"]) <= 3.0
        sgd_story = (
            "torch-default init stays at chance "
            f"(ours {sgd['delta_vs_torch_default_pp']:+.2f}pp ahead — "
            "torch's saturated init cannot escape under "
            "SGD(1e-3)+StepLR(0.1/epoch)), while the lecun-init control "
            "pins the optimizer paths equal "
            f"({sgd['delta_vs_init_control_pp']:+.2f}pp)"
            if ref_at_chance and control_close else
            f"ours vs torch-default "
            f"{sgd['delta_vs_torch_default_pp']:+.2f}pp, vs lecun-init "
            f"control {sgd['delta_vs_init_control_pp']:+.2f}pp — "
            "REVIEW: numbers do not match the init-effect narrative")
    else:
        sgd_story = "NOT RUN (see runs_failed)"
    out["conclusion"] = (
        f"adam: mean delta {out['mean_delta_pp']:+.2f}pp vs per-seed sd "
        f"{out['sd_delta_pp']:.2f}pp ({'within' if adam_ok else 'OUTSIDE'}"
        f" spread); sgd+StepLR: {sgd_story}")
    path = os.path.join(REPO, "PARITY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    log(f"wrote {path}: {out['conclusion']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
