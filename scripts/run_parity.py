#!/usr/bin/env python3
"""Run the full accuracy-parity suite and assemble PARITY.json.

5 Adam seeds (the round-3 protocol, re-pinned on the current tree) plus
one SGD+StepLR seed-pair (ref classif.py:122-131's second optimizer
path) — VERDICT r5 item 4.  Each run shells out to accuracy_parity.py
so ours and the reference see identical corpora per seed.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEEDS = [1234, 7, 99, 41, 2024]
SGD_SEEDS = [1234]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def one(seed: int, optimizer: str) -> dict:
    cmd = [sys.executable, os.path.join(REPO, "scripts",
                                        "accuracy_parity.py"),
           "--dataset", "synthetic_hard", "--seed", str(seed),
           "--optimizer", optimizer,
           "--rsl", f"/tmp/parity_rsl_{optimizer}_{seed}"]
    log(f"=== parity seed {seed} optimizer {optimizer} ===")
    res = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                         timeout=3600)
    if res.returncode != 0:
        log(res.stderr[-4000:])
        raise RuntimeError(f"parity run failed (seed {seed})")
    return json.loads(res.stdout.strip().splitlines()[-1])


def main() -> int:
    runs = [one(s, "adam") for s in SEEDS]
    sgd_runs = [one(s, "sgd") for s in SGD_SEEDS]

    ours = [r["ours"]["test_acc"] for r in runs]
    ref = [r["reference"]["test_acc"] for r in runs]
    deltas = [round((o - r) * 100, 2) for o, r in zip(ours, ref)]
    out = {
        "round": 5,
        "corpus": "synthetic_hard (data/io.py SYNTH_HARD: class_sep 0.45,"
                  " noise 70)",
        "protocol": "2 epochs, batch 64, best-valid-loss model both "
                    "sides, identical corpus/split per seed",
        "n_seeds": len(SEEDS),
        "seeds": SEEDS,
        "ours_test_acc": ours,
        "reference_test_acc": ref,
        "deltas_pp": deltas,
        "mean_ours": round(statistics.mean(ours) * 100, 2),
        "mean_reference": round(statistics.mean(ref) * 100, 2),
        "mean_delta_pp": round(statistics.mean(deltas), 2),
        "sd_delta_pp": round(statistics.stdev(deltas), 2),
        "sd_ours_pp": round(statistics.stdev(ours) * 100, 2),
        "sd_reference_pp": round(statistics.stdev(ref) * 100, 2),
        "sgd": [{
            "seed": r["seed"],
            "ours_test_acc": r["ours"]["test_acc"],
            "reference_test_acc": r["reference"]["test_acc"],
            "delta_pp": round((r["ours"]["test_acc"]
                               - r["reference"]["test_acc"]) * 100, 2),
        } for r in sgd_runs],
        "runs": runs + sgd_runs,
    }
    adam_ok = abs(out["mean_delta_pp"]) <= 2 * out["sd_delta_pp"]
    out["conclusion"] = (
        f"adam: mean delta {out['mean_delta_pp']:+.2f}pp vs per-seed sd "
        f"{out['sd_delta_pp']:.2f}pp ({'within' if adam_ok else 'OUTSIDE'}"
        " spread); sgd+StepLR seed-pair delta "
        f"{out['sgd'][0]['delta_pp']:+.2f}pp")
    path = os.path.join(REPO, "PARITY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    log(f"wrote {path}: {out['conclusion']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
