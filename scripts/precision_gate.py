#!/usr/bin/env python3
"""Precision gate leg (scripts/gate.sh): the PrecisionPolicy contract,
end to end on CPU.

Four stages, all bounded:

  A. preset parity — a short synthetic run per preset (f32 reference,
     then bf16 / bf16_full / f16): every run must finish with finite
     losses, and each preset's loss curve must agree with the f32
     reference within a preset-specific tolerance (bf16 compute noise
     is real; divergence is a policy-plumbing bug).
  B. accumulator provenance — each run's telemetry must carry the
     ``precision_policy`` event, and its ``accum_dtype`` must be
     float32 for EVERY preset: loss/metric accumulation never happens
     in a half dtype (the mixed-precision-accum lint rule's runtime
     counterpart).  The f16 run must also record its loss scale.
  C. fused == unfused — in f32 the fused train step (one jitted
     program: fwd+bwd+optimizer+metrics) must be BIT-identical to the
     diagnostic two-dispatch path over several steps; any drift means
     the fusion changed the math, not just the schedule.
  D. one-program evidence — the fused step AOT-compiles to a single
     executable whose one invocation advances the optimizer (step+1,
     params changed) AND returns the metrics; its cost estimate is
     recorded in the shared costs registry like every other program.

Run as ``env -u XLA_FLAGS JAX_PLATFORMS=cpu python
scripts/precision_gate.py``.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

EPOCHS = 3
# |train_loss - f32 train_loss| allowed per epoch.  bf16 presets round
# activations (and for bf16_full, params) to 8 mantissa bits — on this
# tiny synthetic problem the curves stay close but not equal.  f16 keeps
# f32 master params and scales the loss, so it tracks tighter.
LOSS_TOL = {"bf16": 0.25, "bf16_full": 0.35, "f16": 0.15}


def _events(rsl: str) -> list:
    path = os.path.join(rsl, "telemetry", "rank0.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _policy_events(rsl: str) -> list:
    return [e for e in _events(rsl)
            if e.get("kind") == "event"
            and e.get("name") == "precision_policy"]


def _cfg(rsl: str, preset: str):
    from distributedpytorch_tpu.config import Config

    return Config(action="train", data_path="/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="mlp", batch_size=8,
                  nb_epochs=EPOCHS, debug=True, precision=preset,
                  telemetry=True)


def _curve(result) -> list:
    return [float(h["train_loss"]) for h in result["history"]]


def main() -> int:
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(1)

    import jax
    import numpy as np

    from distributedpytorch_tpu import costs
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.models.registry import get_model
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.precision import PRESET_NAMES, get_policy
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    problems = []
    work = tempfile.mkdtemp(prefix="precision_gate_")

    # -- stage A+B: preset parity + accumulator provenance ------------
    curves = {}
    for preset in PRESET_NAMES:
        rsl = os.path.join(work, preset)
        result = run_train(_cfg(rsl, preset))
        curves[preset] = _curve(result)
        if len(curves[preset]) != EPOCHS \
                or not all(np.isfinite(curves[preset])):
            problems.append(f"{preset}: run did not finish {EPOCHS} "
                            f"finite epochs: {curves[preset]}")
            continue
        pol = _policy_events(rsl)
        if not pol:
            problems.append(f"{preset}: no precision_policy telemetry "
                            f"event")
            continue
        ev = pol[-1].get("attrs", {})
        if ev.get("preset") != preset:
            problems.append(f"{preset}: telemetry preset mismatch: "
                            f"{ev.get('preset')!r}")
        if ev.get("accum_dtype") != "float32":
            problems.append(
                f"{preset}: accum_dtype is {ev.get('accum_dtype')!r}, "
                f"not float32 — loss/metric accumulators must be f32 "
                f"under every preset")
        want_param = {"f32": "float32", "bf16": "float32",
                      "bf16_full": "bfloat16", "f16": "float32"}[preset]
        if ev.get("param_dtype") != want_param:
            problems.append(f"{preset}: param_dtype "
                            f"{ev.get('param_dtype')!r} != {want_param}")
        if preset == "f16" and not ev.get("loss_scale"):
            problems.append("f16: telemetry records no loss scale")
        print(f"precision gate A: {preset} curve "
              f"{[round(c, 4) for c in curves[preset]]}")

    ref = curves.get("f32")
    if ref:
        for preset, tol in LOSS_TOL.items():
            got = curves.get(preset)
            if not got or len(got) != len(ref):
                continue  # already reported above
            worst = max(abs(a - b) for a, b in zip(got, ref))
            if worst > tol:
                problems.append(
                    f"{preset}: loss curve diverges from f32 by "
                    f"{worst:.4f} (tol {tol}) — policy plumbing bug, "
                    f"not rounding noise")
            else:
                print(f"precision gate A: {preset} vs f32 max epoch "
                      f"delta {worst:.4f} (tol {tol})")

    # -- stage C: fused == unfused, bit-identical in f32 --------------
    pol = get_policy("f32")
    model = get_model("mlp", 10, precision=pol)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)

    def build():
        eng = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                     0.13, 0.3, 28, precision=pol)
        return eng, eng.init_state(jax.random.PRNGKey(0))

    rng = np.random.default_rng(7)
    batches = [(rng.integers(0, 255, (8, 28, 28, 3)).astype(np.uint8),
                rng.integers(0, 10, (8,)).astype(np.int32),
                np.ones((8,), bool)) for _ in range(4)]
    key = jax.random.PRNGKey(11)

    eng_f, st_f = build()
    for imgs, labels, valid in batches:
        st_f, _ = eng_f.train_step(st_f, imgs, labels, valid, key)
    eng_u, st_u = build()
    for imgs, labels, valid in batches:
        st_u, _ = eng_u.train_step_unfused(st_u, imgs, labels, valid,
                                           key)
    leaves_f = jax.tree_util.tree_leaves(jax.device_get(st_f.params))
    leaves_u = jax.tree_util.tree_leaves(jax.device_get(st_u.params))
    bitwise = all(
        np.array_equal(np.asarray(a).view(np.uint8),
                       np.asarray(b).view(np.uint8))
        for a, b in zip(leaves_f, leaves_u))
    if not bitwise:
        worst = max(float(np.max(np.abs(np.asarray(a, np.float64)
                                        - np.asarray(b, np.float64))))
                    for a, b in zip(leaves_f, leaves_u))
        problems.append(f"fused vs unfused params differ in f32 "
                        f"(max |delta| {worst:.3e}) — fusion changed "
                        f"the math")
    else:
        print(f"precision gate C: fused == unfused bit-identical over "
              f"{len(batches)} f32 steps")

    # -- stage D: the fused step is ONE compiled program --------------
    costs.reset()
    eng_d, st_d = build()
    imgs, labels, valid = batches[0]
    compiled = eng_d.train_step.lower(st_d, imgs, labels, valid,
                                      key).compile()
    costs.record("train_step_fused", compiled)
    st_after, metrics = compiled(st_d, imgs, labels, valid, key)
    if int(jax.device_get(st_after.step)) != 1:
        problems.append("fused program did not advance the optimizer "
                        "step in its single invocation")
    if not set(metrics) >= {"loss", "correct", "valid"}:
        problems.append(f"fused program returned incomplete metrics: "
                        f"{sorted(metrics)}")
    if "train_step_fused" not in costs.registry():
        problems.append("fused step not recorded in the costs registry")
    else:
        print("precision gate D: one executable ran fwd+bwd+optimizer"
              "+metrics and is cost-registered")

    if problems:
        print("precision gate RED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("precision gate GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
