"""L0: configuration.

The reference keeps a flat constants module star-imported everywhere
(ref: config.py:1-55, imported at main.py:16, classif.py:22, dataloader.py:21,
utils.py:22) plus argparse overrides (ref: main.py:20-58).  Here the same
surface is a frozen dataclass produced by ``Config.from_args``; there are no
mutable module globals, so the reference's ``DEBUG`` rebind wart
(ref: main.py:115 — the flag never reaches spawned children) cannot recur.

Defaults mirror ref config.py exactly where a TPU equivalent exists:
MODEL_NAME='resnet' (:26), OPTIMIZER='adam' (:28), LOSS='cross_entropy'
(:30), RSL_PATH='./rsl' (:34), LOG_FILE='test.log' (:36), NB_EPOCHS=2 (:38),
BATCH_SIZE=64 (:40), SEED=1234 (:44), FEATURE_EXTRACT=False (:48),
USE_PRETRAINED=False (:51).

Deliberate divergences (documented in README):
  * ``-d/--data_path`` is *honored* (the reference requires it but then reads
    the DATA_PATH constant — SURVEY defect #1, ref classif.py:98,217).
  * The DDTNodes address table / MASTER_ADDR / MASTER_PORT (ref config.py:15-24)
    have no equivalent: TPU topology is discovered from the runtime.
  * NUM_WORKERS becomes device prefetch depth.  NUM_THREADS (ref
    config.py:54, torch.set_num_threads on the CPU fallback) is obviated:
    XLA manages its own host thread pools.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Optional

# Reference defaults (ref: config.py)
DEBUG = False               # ref config.py:9
MODEL_NAME = "resnet"       # ref config.py:26
OPTIMIZER = "adam"          # ref config.py:28
LOSS = "cross_entropy"      # ref config.py:30
DATA_PATH = "./data"        # ref config.py:32
RSL_PATH = "./rsl"          # ref config.py:34
LOG_FILE = "test.log"       # ref config.py:36
NB_EPOCHS = 2               # ref config.py:38
BATCH_SIZE = 64             # ref config.py:40 (per-process, as in the ref)
NUM_WORKERS = 2             # ref config.py:42 (prefetch depth here)
SEED = 1234                 # ref config.py:44
FEATURE_EXTRACT = False     # ref config.py:48
USE_PRETRAINED = False      # ref config.py:51

VALID_RATIO = 0.9           # ref dataloader.py:23
DEBUG_SUBSET = 200          # ref dataloader.py:141

MODEL_CHOICES = (
    "cnn", "mlp", "resnet", "alexnet", "vgg", "squeezenet", "densenet",
    "inception", "vit",
)
OPTIMIZER_CHOICES = ("adam", "SGD")
LOSS_CHOICES = ("cross_entropy", "weighted_cross_entropy", "focal_loss")
DATASET_CHOICES = ("mnist", "fashion_mnist", "cifar10", "synthetic",
                   "synthetic_hard")


@dataclasses.dataclass(frozen=True)
class Config:
    """Everything a run needs; replaces ref config.py + parsed args."""

    action: str = "train"                  # 'train' | 'test' | 'serve'
    data_path: str = DATA_PATH             # honored (fixes SURVEY defect #1)
    rsl_path: str = RSL_PATH
    log_file: str = LOG_FILE
    dataset: str = "mnist"
    model_name: str = MODEL_NAME
    optimizer: str = OPTIMIZER
    loss: str = LOSS
    batch_size: int = BATCH_SIZE           # per-process batch, ref semantics
    nb_epochs: int = NB_EPOCHS
    learning_rate: float = 1e-3            # ref classif.py:124,126
    momentum: float = 0.9                  # ref classif.py:126
    lr_step_gamma: float = 0.1             # ref classif.py:128 (StepLR, SGD only)
    seed: int = SEED
    feature_extract: bool = FEATURE_EXTRACT
    use_pretrained: bool = USE_PRETRAINED
    # Torch state_dict (.pth) to initialize the backbone from; required when
    # use_pretrained=True (no network access — weights are never downloaded).
    pretrained_path: Optional[str] = None
    checkpoint_file: Optional[str] = None  # -f: resume (train) / model (test)
    debug: bool = DEBUG                    # 200-sample subset, ref dataloader.py:139-144
    prefetch: int = NUM_WORKERS            # device prefetch depth
    # Background host-pipeline threads for the streaming loader: the
    # per-step numpy gather + device_put dispatch move off the driver
    # thread onto N producers feeding bounded queues (byte-identical
    # batch order to the synchronous path).  0 = synchronous production
    # on the consumer thread (the pre-overlap behavior, and what direct
    # ShardedLoader constructions default to).
    producer_threads: int = 1
    # Device-side double-buffered prefetch for the streaming loader: a
    # dedicated transfer thread issues the sharded device_put for the
    # next N batches into a bounded device queue while the current step
    # computes, so H2D overlaps compute.  Composes with
    # producer_threads (producers then gather host arrays only; the
    # transfer thread owns all device placement, keeping batch order
    # byte-identical).  0 = no device-side stage (prior behavior).
    device_prefetch: int = 0
    # Non-blocking checkpoint saves: only the host snapshot blocks the
    # driver; serialization/file-I/O run on a background writer joined at
    # the next save, preemption, or exit (checkpoint.AsyncSaver).  The
    # .tmp->rename crash-safety protocol and the on-disk bytes are
    # identical to the synchronous path.
    ckpt_async: bool = False
    # Persistent XLA compilation cache (runtime.configure_compilation_
    # cache): None -> RSL_PATH/xla_cache unless no_compile_cache.  A
    # second run of the same config skips every XLA compile (disk hit).
    compilation_cache_dir: Optional[str] = None
    no_compile_cache: bool = False
    # Lower+compile the train/eval programs against abstract batch shapes
    # BEFORE epoch 0 (AOT), so step-1 latency is bounded and recorded
    # (compile/warmup_s + compile/cache_hit telemetry gauges).
    aot_warmup: bool = False
    half_precision: bool = True            # bfloat16 compute on TPU (MXU-native)
    # Explicit mixed-precision preset (precision.PRESETS: f32 | bf16 |
    # bf16_full | f16).  None derives the policy from the legacy
    # half_precision bool (True -> bf16, False -> f32), so every
    # programmatic Config(half_precision=...) construction keeps its exact
    # historical behavior.  --precision and --no-bf16 conflict unless they
    # agree (validated in cli.run_train/run_test).
    precision: Optional[str] = None
    # Gradient rematerialization: 'none' (default), 'blocks' (nn.remat at
    # the zoo block boundaries — vit/densenet/inception — or a
    # save-matmul-outputs jax.checkpoint around the whole apply for flat
    # models), 'full' (checkpoint everything; max memory relief).
    remat: str = "none"
    # Scan-over-layers: stack homogeneous repeated-block params on a
    # leading (depth,) axis and run the blocks under lax.scan
    # (vgg/densenet/inception + the vit family), collapsing O(depth)
    # HLO into O(1) — smaller programs, faster AOT warmup.  Composes
    # with --remat blocks (nn.remat inside the scan body).  Checkpoints
    # self-describe the layout and convert across the flag in both
    # directions (checkpoint.py / models/scan.py).
    scan_layers: bool = False
    focal_gamma: float = 2.0               # ref utils.py:144
    # 'resident': split lives in HBM, one XLA dispatch per epoch;
    # 'stream': host batching + prefetch; 'auto' picks by size.
    data_mode: str = "auto"
    # Opt-in: train on the deterministic synthetic corpus when the real
    # dataset's raw files are absent (otherwise that is a CLI error).
    synthetic_fallback: bool = False
    resident_max_bytes: int = 512 * 1024 * 1024
    profile: bool = False                  # jax.profiler trace of one epoch
    # Structured telemetry (telemetry.py): per-rank JSONL metrics under
    # RSL_PATH/telemetry/ — epoch/dispatch spans, data-wait counters,
    # checkpoint durations, throughput + MFU gauges.  Off by default:
    # the disabled path does no file I/O and adds no per-step work.
    telemetry: bool = False
    # Fuse K (train+valid) epochs into one XLA dispatch (resident mode
    # only).  K>1 amortizes dispatch latency; checkpoints are then written
    # per chunk instead of per epoch.  1 = exact reference cadence.
    epochs_per_dispatch: int = 1
    # Accumulate gradients over K microbatches per optimizer step (ABSENT
    # in the reference); cuts activation memory to batch/K per step.
    grad_accum: int = 1
    # 'msgpack': single-file reference-contract checkpoints (default);
    # 'orbax': directory checkpoints, sharded state saved as-laid-out
    # (no gather) — see checkpoint.py.
    ckpt_format: str = "msgpack"
    # Fold the devices into a 2-D (data, model) mesh and shard large
    # param/optimizer tensors over the 'model' axis (ZeRO/FSDP-style,
    # see parallel.py).  1 = pure data parallelism (reference semantics).
    model_parallel: int = 1
    # Third mesh axis for the ring x pipeline composition: tokens
    # sharded over an N-way 'seq' axis with ring attention inside each
    # pipeline stage (vit_pipeline.make_pipeline_fn(ring=True)).
    # 1 = no seq axis (2-D mesh).  Requires --pipeline-parallel +
    # --attention ring; data_parallel becomes
    # world / (model_parallel * seq_parallel).
    seq_parallel: int = 1
    # 'full': XLA softmax attention on each device (default);
    # 'ring': sequence-parallel ring attention over the 'model' mesh axis
    # (vit only, needs model_parallel >= 2 — see ops/attention.py);
    # 'flash': the Pallas flash-attention TPU kernel, O(S) memory
    # (vit only — see ops/flash_attention.py);
    # 'ring_flash': the composition — ring sequence parallelism whose
    # per-shard local attention runs the Pallas kernel (O(S_local)
    # memory AND kernel speed; needs model_parallel >= 2).
    attention: str = "full"
    # Megatron-style tensor parallelism for vit: attention heads + MLP
    # hidden sharded over 'model' with SHARDED ACTIVATIONS (parallel.py
    # strategy 2).  Needs model_parallel >= 2; exclusive with ring.
    tensor_parallel: bool = False
    # GPipe stage parallelism for vit: transformer blocks sharded over
    # 'model' as pipeline stages, activations handed stage-to-stage via
    # ppermute (models/vit_pipeline.py).  Needs model_parallel >= 2;
    # exclusive with ring/flash/tensor-parallel.
    pipeline_parallel: bool = False
    # Microbatches per pipeline step (GPipe M).  0 = one per stage (the
    # minimum).  Larger M shrinks the bubble fraction
    # (P-1)/(M+P-1) at the cost of smaller per-tick matmuls; the
    # per-device batch must be divisible by M.
    pipeline_microbatches: int = 0
    # > 0 replaces the vit MLPs with switch mixture-of-experts layers of
    # that many experts (models/moe.py) — expert-PARALLEL over the
    # 'model' mesh axis when --model-parallel >= 2, replicated experts
    # otherwise.  Exclusive with --tensor-parallel/--pipeline-parallel.
    moe_experts: int = 0
    # Fault injection + retry policy (faults.py, ISSUE 5).  fault_plan is
    # the DSL string "site:kind:after_n[:count]" (';'-separated) or the
    # path of a JSON plan file; None (the default) installs NO plan and
    # keeps every injection site zero-cost.  fault_seed feeds the plan
    # and the deterministic retry-jitter stream.
    fault_plan: Optional[str] = None
    fault_seed: int = 0
    # Retry policy for the transient-failure sites (dataset reads,
    # checkpoint write/restore, distributed init): attempts per site,
    # first backoff delay (doubles per attempt, jittered), and the
    # per-site wall-clock deadline after which no new attempt starts.
    retry_max_attempts: int = 3
    retry_base_delay: float = 0.05
    retry_timeout: float = 60.0
    # Elastic training (elastic.py, ISSUE 10 + 13): survive rank loss
    # by reconfiguring into the surviving world instead of exiting at
    # the failure agreement, and grow the world back when join claims
    # appear.  elastic_dir is the shared rendezvous dir (default
    # RSL_PATH/elastic); health_timeout bounds the boundary
    # agree_health allgather so a dead peer becomes a local verdict
    # instead of a deadlock (0 = unbounded, the pre-elastic behavior);
    # max_reconfigures caps reconfigure rounds (shrink or grow) per
    # process.  elastic_target is the autoscaling policy ('capacity'
    # admits every join claim, 'fixed:N' admits up to a world of N);
    # elastic_min_world declines join batches that would still leave
    # the world under the floor; elastic_join makes THIS process a
    # joiner: drop a claim in elastic_dir and enter the world the
    # coordinator admits it into instead of initializing one.
    elastic: bool = False
    elastic_dir: Optional[str] = None
    health_timeout: float = 0.0
    max_reconfigures: int = 3
    elastic_target: str = "capacity"
    elastic_min_world: int = 1
    elastic_join: bool = False
    # How long a joiner polls for the coordinator's admit/decline
    # verdict before giving up (emits elastic/join_wait_timeout, then
    # raises).  Must dominate an epoch plus a reconfigure window —
    # survivors only scan claims at health boundaries.
    elastic_join_wait: float = 600.0
    # Rolling-checkpoint lineage depth: how many per-epoch snapshots are
    # retained (1 = the reference delete-previous behavior; >1 gives the
    # corruption-fallback resume earlier snapshots to walk back to).
    keep_ckpts: int = 1
    # 'lint' subcommand (analysis/ graftlint): machine-readable output
    # and an optional focused path list (empty = the full repo scope).
    lint_json: bool = False
    lint_paths: tuple = ()
    lint_changed_only: bool = False   # findings only in git-changed files
    lint_base: str = ""               # --changed-only diff base ref
    # 'sim' subcommand (sim/ fleet simulator): scenario name or JSON
    # path, seed, and optional overrides of the scenario's fleet size /
    # virtual duration / latency-model file.  Artifacts land under
    # rsl_path in the same JSONL schemas live runs write.
    sim_scenario: str = "control"
    sim_seed: int = 0
    sim_replicas: int = 0        # 0 = scenario default
    sim_duration: float = 0.0    # virtual seconds; 0 = scenario default
    sim_model: Optional[str] = None  # latency-model JSON override
    # Flight recorder (flightrec.py, ISSUE 7): a fixed-memory per-rank
    # ring buffer of per-step records (step/dispatch/data-wait times,
    # queue depth, retry/fault events) dumped to
    # RSL_PATH/flightrec-rank<N>.json on crash/preempt/peer-failure and
    # at run end.  ON by default — the black box is only useful if it
    # was recording when things went wrong; the per-step cost is a
    # bounded deque append (budgeted by scripts/anomaly_gate.py).
    flightrec: bool = True
    flightrec_ring: int = 4096
    # Anomaly-triggered profiling: watch per-step time with a rolling
    # median/MAD window (+ starvation and retry-burst triggers) and fire
    # a bounded number of programmatic jax.profiler captures of the next
    # K steps into RSL_PATH/anomaly_traces/.  Opt-in; requires the
    # flight recorder (the capture is explained by its records).
    anomaly_capture: bool = False
    anomaly_window: int = 32               # rolling baseline, steps
    anomaly_mad_k: float = 8.0             # excess > mad_k * MAD ...
    anomaly_rel_factor: float = 3.0        # ... AND step > rel * median
    anomaly_min_excess: float = 0.05       # absolute excess floor, sec
    anomaly_capture_steps: int = 4         # K steps per capture
    anomaly_max_captures: int = 2          # per-run capture budget
    # 'timeline' subcommand: merged Chrome trace-event output path
    # (default RSL_PATH/timeline.json).
    timeline_out: Optional[str] = None
    # 'roofline' subcommand (roofline.py): per-op trace attribution.
    # trace_dir overrides the RSL_PATH/trace default; from_anomaly
    # analyzes the newest anomaly capture instead.
    roofline_trace_dir: Optional[str] = None
    roofline_from_anomaly: bool = False
    roofline_top: int = 20
    # 'bench-trend' subcommand (benchtrend.py): regression ledger over
    # BENCH_r*.json; exit 1 when the latest fresh-vs-fresh delta drops
    # more than trend_threshold (fractional).
    trend_dir: Optional[str] = None
    trend_threshold: float = 0.05
    # Machine-readable output for the offline report subcommands
    # (telemetry/roofline/bench-trend --json).
    report_json: bool = False
    # Live monitoring: serve Prometheus text at
    # http://0.0.0.0:(metrics_port + rank)/metrics (and /healthz) for the
    # life of the run.  0 disables the exporter.
    metrics_port: int = 0
    # 'serve' subcommand (serving/, ISSUE 15): each process answers
    # POST /predict on serve_port + its INITIAL rank (bound once; kept
    # across elastic reconfigures).  serve_buckets is the fixed menu of
    # AOT-compiled batch sizes; serve_max_latency_ms the micro-batcher
    # flush deadline; serve_queue the bounded queue depth past which
    # requests are shed with a 503; serve_request_timeout the handler-
    # side wait before a 504; serve_max_requests stops the driver after
    # answering N requests (0 = serve forever; the gates use N).
    serve_port: int = 8100
    serve_buckets: str = "1,4,16,64"
    serve_max_latency_ms: float = 20.0
    serve_queue: int = 256
    serve_request_timeout: float = 30.0
    serve_max_requests: int = 0
    # 'fleet' subcommand (fleet.py, ISSUE 16): the standalone collector
    # scrapes the per-rank exporters at metrics_port..metrics_port +
    # fleet_ranks - 1 every fleet_interval seconds, ages a silent rank
    # out of the merged series after fleet_stale_after consecutive
    # failed scrapes, re-exports fleet /metrics + /fleet on fleet_port,
    # and (with --slo-spec) evaluates burn-rate objectives, writing one
    # incident-*.json bundle per newly-firing objective.
    # fleet_max_cycles bounds the run for gates (0 = run until ^C);
    # slo_spec also feeds 'incidents' for offline re-reporting.
    fleet_ranks: int = 1
    fleet_port: int = 9200
    fleet_interval: float = 1.0
    fleet_stale_after: int = 3
    fleet_max_cycles: int = 0
    slo_spec: Optional[str] = None
    # 'frontdoor' subcommand (serving/frontdoor.py, ISSUE 19): one
    # client port over fd_ranks serve replicas (predict at serve_port+i,
    # health at metrics_port+i /healthz — or /livez on the serve port
    # when no exporter).  Health-aware routing ejects a replica after
    # fd_eject_after consecutive probe failures (or a last_step_age_s
    # above fd_max_step_age; 0 disables the staleness check) and
    # readmits it on recovery; admission sheds with a 503 + Retry-After
    # once fd_pending_budget in-flight requests are queued fleet-wide.
    # --autoscale turns on the controller (queue/shed/SLO-verdict
    # pressure -> launch via --launch-cmd; calm -> graceful drain),
    # clamped to [fd_min_world, fd_max_world or fd_ranks] with
    # hysteresis (fd_up_hold/fd_down_hold/fd_cooldown).  --rollout
    # watches fd_watch_dir (default RSL_PATH) for a newer
    # lineage-verified checkpoint and canaries it on a fd_canary_*
    # fraction of replicas, promoting or rolling back on the
    # canary-vs-stable error-rate/p95 comparison.  fd_max_cycles bounds
    # the control loop for gates (0 = run until ^C).
    fd_port: int = 8080
    fd_ranks: int = 1
    fd_min_world: int = 1
    fd_max_world: int = 0
    fd_interval: float = 0.5
    fd_upstream_timeout: float = 10.0
    fd_pending_budget: int = 64
    fd_retry_after: float = 1.0
    fd_eject_after: int = 3
    fd_max_step_age: float = 0.0
    fd_max_cycles: int = 0
    fd_autoscale: bool = False
    fd_queue_high: float = 8.0
    fd_queue_low: float = 1.0
    fd_up_hold: float = 2.0
    fd_down_hold: float = 10.0
    fd_cooldown: float = 5.0
    fd_launch_cmd: Optional[str] = None
    fd_rollout: bool = False
    fd_watch_dir: Optional[str] = None
    fd_canary_fraction: float = 0.34
    fd_canary_hold: float = 5.0
    fd_canary_min_requests: int = 20
    fd_canary_max_error: float = 0.05
    fd_canary_p95_factor: float = 3.0

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    def precision_policy(self):
        """The resolved precision.PrecisionPolicy for this config."""
        from .precision import from_flags

        return from_flags(self.precision, self.half_precision)

    def compilation_cache_path(self) -> Optional[str]:
        """The effective persistent-cache dir: the explicit override, the
        RSL_PATH/xla_cache default, or None under --no-compile-cache."""
        if self.no_compile_cache:
            return None
        if self.compilation_cache_dir:
            return self.compilation_cache_dir
        return os.path.join(self.rsl_path, "xla_cache")


def _common_args(p: argparse.ArgumentParser) -> None:
    """Flags shared by train and test (ref: main.py:23-33)."""
    p.add_argument("--debug", action="store_true", dest="debug",
                   default=DEBUG, help="debug mode (200-sample train subset)")
    p.add_argument("-d", "--data_path", metavar="data_path", type=str,
                   dest="dataPath", default=None, required=True,
                   help="data path")
    p.add_argument("-b", "--batchSize", metavar="N", type=int,
                   dest="batchSize", default=BATCH_SIZE,
                   help=f"batch size (default: {BATCH_SIZE})")
    # TPU-rebuild extensions beyond the reference CLI:
    p.add_argument("--dataset", choices=DATASET_CHOICES, default="mnist",
                   help="dataset to load (default: mnist)")
    p.add_argument("--model", choices=MODEL_CHOICES, default=MODEL_NAME,
                   dest="modelName",
                   help=f"model architecture (default: {MODEL_NAME})")
    p.add_argument("--optimizer", choices=OPTIMIZER_CHOICES,
                   default=OPTIMIZER, help=f"optimizer (default: {OPTIMIZER})")
    p.add_argument("--loss", choices=LOSS_CHOICES, default=LOSS,
                   help=f"loss (default: {LOSS})")
    p.add_argument("--rsl_path", type=str, default=RSL_PATH,
                   help=f"results/checkpoint dir (default: {RSL_PATH})")
    p.add_argument("--no-bf16", action="store_true",
                   help="disable bfloat16 compute (use float32; "
                        "equivalent to --precision f32)")
    p.add_argument("--precision",
                   choices=("f32", "bf16", "bf16_full", "f16"),
                   default=None,
                   help="mixed-precision preset: f32 (all float32), bf16 "
                        "(f32 master params, bfloat16 compute, f32 "
                        "accumulation — the default behavior), bf16_full "
                        "(bf16 master params too; halves param+optimizer "
                        "memory), f16 (float16 compute with dynamic loss "
                        "scaling; non-TPU backends only)")
    p.add_argument("--remat", choices=("none", "blocks", "full"),
                   default="none",
                   help="gradient rematerialization: blocks = recompute "
                        "each zoo block's interior in backward keeping "
                        "matmul outputs (vit/densenet/inception blocks; "
                        "whole-apply checkpoint for flat models), full = "
                        "save nothing (max activation-memory relief; "
                        "backward recomputes the forward)")
    p.add_argument("--data-mode", choices=("auto", "stream", "resident"),
                   default="auto", dest="dataMode",
                   help="device-resident vs streamed batches (default: auto)")
    p.add_argument("--prefetch", type=int, default=NUM_WORKERS, metavar="N",
                   help="streamed-mode device prefetch depth (the ref "
                        f"NUM_WORKERS analogue; default {NUM_WORKERS}; "
                        "0 = strictly synchronous)")
    p.add_argument("--producer-threads", type=int, default=1, metavar="N",
                   dest="producerThreads",
                   help="streamed-mode background host-pipeline threads "
                        "(gather + device_put off the driver thread; "
                        "batch order stays byte-identical; default 1; "
                        "0 = produce synchronously on the driver)")
    p.add_argument("--device-prefetch", type=int, default=0, metavar="N",
                   dest="devicePrefetch",
                   help="streamed-mode device-side double-buffer depth: "
                        "a transfer thread issues the sharded device_put "
                        "for the next N batches while the current step "
                        "computes (H2D overlaps compute; batch order "
                        "stays byte-identical; composes with "
                        "--producer-threads; default 0 = off)")
    p.add_argument("--scan-layers", action="store_true", dest="scanLayers",
                   help="stack homogeneous repeated-block params and run "
                        "them under lax.scan (vgg/densenet/inception/vit "
                        "family): O(depth) HLO collapses to O(1) for "
                        "faster compiles; gradients match the unscanned "
                        "model; checkpoints convert across the flag")
    p.add_argument("--ckpt-async", action="store_true", dest="ckptAsync",
                   help="non-blocking checkpoint saves: serialization + "
                        "file I/O run on a background writer joined at "
                        "the next save/preemption/exit (same bytes, same "
                        "crash-safety as sync)")
    p.add_argument("--compilation-cache-dir", type=str, default=None,
                   dest="compilationCacheDir", metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(default RSL_PATH/xla_cache)")
    p.add_argument("--no-compile-cache", action="store_true",
                   dest="noCompileCache",
                   help="disable the persistent XLA compilation cache")
    p.add_argument("--aot-warmup", action="store_true", dest="aotWarmup",
                   help="AOT-compile the train/eval programs against "
                        "abstract batch shapes before epoch 0 (records "
                        "compile/warmup_s + compile/cache_hit telemetry "
                        "gauges)")
    p.add_argument("--fault-plan", type=str, default=None,
                   dest="faultPlan", metavar="PLAN",
                   help="fault-injection plan: "
                        "'site:kind:after_n[:count[:stall_s]]' "
                        "(';'-separated, e.g. 'data.read:ioerror:2') or a "
                        "JSON plan file; sites: data.read data.host_batch "
                        "ckpt.save ckpt.finalize ckpt.restore runtime.init "
                        "elastic.reinit elastic.join elastic.grow_reinit "
                        "telemetry.write; kinds: ioerror fatal preempt "
                        "torn stall rank_loss rank_join (default: no "
                        "faults, zero overhead)")
    p.add_argument("--fault-seed", type=int, default=0, dest="faultSeed",
                   metavar="S",
                   help="seed for the fault plan + deterministic retry "
                        "jitter (default 0)")
    p.add_argument("--retry-max-attempts", type=int, default=3,
                   dest="retryMaxAttempts", metavar="N",
                   help="attempts per transient-failure site (dataset "
                        "reads, checkpoint I/O, distributed init) before "
                        "giving up (default 3)")
    p.add_argument("--retry-base-delay", type=float, default=0.05,
                   dest="retryBaseDelay", metavar="SEC",
                   help="first retry backoff delay in seconds; doubles "
                        "per attempt with deterministic jitter "
                        "(default 0.05)")
    p.add_argument("--retry-timeout", type=float, default=60.0,
                   dest="retryTimeout", metavar="SEC",
                   help="per-site wall-clock retry deadline: no new "
                        "attempt starts after this many seconds "
                        "(default 60)")
    p.add_argument("--elastic", action="store_true",
                   help="survive rank loss: on peer failure the healthy "
                        "ranks checkpoint state they hold, re-elect a "
                        "coordinator, re-init jax.distributed as the "
                        "smaller surviving world and resume from the "
                        "newest verified checkpoint (see elastic.py; "
                        "coordinator loss is not survivable)")
    p.add_argument("--elastic-dir", type=str, default=None,
                   dest="elasticDir", metavar="DIR",
                   help="shared rendezvous directory for --elastic "
                        "(claim files + world.json; default "
                        "RSL_PATH/elastic — already shared, the "
                        "checkpoints live there)")
    p.add_argument("--health-timeout", type=float, default=0.0,
                   dest="healthTimeout", metavar="SEC",
                   help="bound the boundary health agreement: if the "
                        "agree_health allgather does not complete in "
                        "SEC seconds, treat it as a peer loss locally "
                        "(reconfigure under --elastic, exit loudly "
                        "otherwise) instead of hanging on a dead rank "
                        "(default 0 = unbounded)")
    p.add_argument("--max-reconfigures", type=int, default=3,
                   dest="maxReconfigures", metavar="N",
                   help="cap on elastic reconfigure rounds (shrink or "
                        "grow) per process; exceeding it exits with the "
                        "underlying error (default 3)")
    p.add_argument("--elastic-target", type=str, default="capacity",
                   dest="elasticTarget", metavar="POLICY",
                   help="autoscaling admission policy for join claims "
                        "at each health boundary: 'capacity' admits "
                        "every claim (scale to whatever shows up), "
                        "'fixed:N' admits only up to a world of N "
                        "(default capacity)")
    p.add_argument("--elastic-min-world", type=int, default=1,
                   dest="elasticMinWorld", metavar="N",
                   help="floor for elastic grow admissions: a join "
                        "batch whose admission would still leave the "
                        "world below N is declined whole — the "
                        "reconfigure window is not worth paying "
                        "(default 1)")
    p.add_argument("--elastic-join", action="store_true",
                   dest="elasticJoin",
                   help="join a running --elastic world instead of "
                        "initializing one: drop a join claim in "
                        "--elastic-dir, wait for the coordinator's "
                        "admit/decline verdict, and enter the grown "
                        "world at the rank it assigns (fresh capacity "
                        "or a departed rank restarting)")
    p.add_argument("--elastic-join-wait", type=float, default=600.0,
                   dest="elasticJoinWait", metavar="S",
                   help="how long a joiner waits for the coordinator's "
                        "admit/decline verdict before emitting "
                        "elastic/join_wait_timeout and giving up; must "
                        "dominate an epoch plus a reconfigure window "
                        "(default 600)")
    p.add_argument("--keep-ckpts", type=int, default=1, dest="keepCkpts",
                   metavar="K",
                   help="rolling-checkpoint lineage depth: retain the K "
                        "newest per-epoch snapshots so corrupted heads "
                        "can fall back to an older valid one (default 1 "
                        "= delete-previous reference behavior)")
    p.add_argument("--feature-extract", action="store_true",
                   dest="featureExtract", default=FEATURE_EXTRACT,
                   help="freeze the backbone, train only the classifier "
                        "head (ref FEATURE_EXTRACT)")
    p.add_argument("--use-pretrained", action="store_true",
                   dest="usePretrained", default=USE_PRETRAINED,
                   help="initialize the backbone from --pretrained-path "
                        "(a torchvision state_dict; ref USE_PRETRAINED)")
    p.add_argument("--pretrained-path", type=str, default=None,
                   dest="pretrainedPath", metavar="FILE",
                   help="torch .pth state_dict for --use-pretrained "
                        "(never downloaded)")
    p.add_argument("--synthetic-fallback", action="store_true",
                   dest="syntheticFallback",
                   help="use the deterministic synthetic corpus when the "
                        "real dataset's raw files are absent (default: "
                        "error out)")
    p.add_argument("--profile", action="store_true",
                   help="write a jax.profiler trace of the second epoch "
                        "to RSL_PATH/trace")
    p.add_argument("--telemetry", action="store_true",
                   help="emit structured JSONL telemetry (spans, "
                        "data-wait/step timing, checkpoint durations, "
                        "throughput + MFU) to RSL_PATH/telemetry/"
                        "rank<N>.jsonl; summarize with "
                        "'main.py telemetry --rsl_path DIR'")
    p.add_argument("--flightrec", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="per-rank ring-buffer flight recorder: per-step "
                        "step/dispatch/data-wait timing + retry/fault "
                        "events, dumped to RSL_PATH/flightrec-rank<N>"
                        ".json on crash/preempt/peer-failure and at run "
                        "end (default: on; --no-flightrec disables)")
    p.add_argument("--flightrec-ring", type=int, default=4096,
                   dest="flightrecRing", metavar="N",
                   help="flight-recorder ring size: the last N step/"
                        "event records are kept (fixed memory; "
                        "default 4096)")
    p.add_argument("--metrics-port", type=int, default=0,
                   dest="metricsPort", metavar="PORT",
                   help="serve live Prometheus metrics while the run is "
                        "alive: each rank binds PORT+rank and answers "
                        "/metrics (counters, gauges, step-time "
                        "p50/p95/p99, goodput category totals) and "
                        "/healthz (rank, world size, elastic generation, "
                        "last-step age); 0 disables (default)")
    p.add_argument("--anomaly-capture", action="store_true",
                   dest="anomalyCapture",
                   help="profile anomalies automatically: when a step "
                        "goes anomalous (rolling median/MAD step-time "
                        "outlier, data starvation, or a retry burst) "
                        "capture the next K steps with jax.profiler "
                        "into RSL_PATH/anomaly_traces/ and emit an "
                        "'anomaly' telemetry event (requires the flight "
                        "recorder)")
    p.add_argument("--anomaly-window", type=int, default=32,
                   dest="anomalyWindow", metavar="W",
                   help="anomaly baseline: rolling window of the last W "
                        "step times (no judgments until full; "
                        "default 32)")
    p.add_argument("--anomaly-mad-k", type=float, default=8.0,
                   dest="anomalyMadK", metavar="K",
                   help="anomaly threshold: a step is anomalous when its "
                        "excess over the window median exceeds K*MAD "
                        "(and the absolute floor; default 8.0)")
    p.add_argument("--anomaly-min-excess", type=float, default=0.05,
                   dest="anomalyMinExcess", metavar="SEC",
                   help="absolute floor on the step-time excess before "
                        "an anomaly fires — keeps scheduler jitter on "
                        "millisecond steps quiet (default 0.05)")
    p.add_argument("--anomaly-capture-steps", type=int, default=4,
                   dest="anomalyCaptureSteps", metavar="K",
                   help="steps per anomaly-triggered profiler capture "
                        "(default 4)")
    p.add_argument("--anomaly-max-captures", type=int, default=2,
                   dest="anomalyMaxCaptures", metavar="N",
                   help="per-run budget of anomaly-triggered captures — "
                        "a pathological run cannot fill the disk with "
                        "traces (default 2)")
    p.add_argument("--epochs-per-dispatch", type=int, default=1,
                   dest="epochsPerDispatch", metavar="K",
                   help="fuse K train+valid epochs per XLA dispatch "
                        "(resident mode; checkpoints then written per "
                        "chunk; default 1)")
    p.add_argument("--ckpt-format", choices=("msgpack", "orbax"),
                   default="msgpack", dest="ckptFormat",
                   help="checkpoint format: single msgpack file (default) "
                        "or an orbax directory with sharded-as-laid-out "
                        "state")
    p.add_argument("--grad-accum", type=int, default=1,
                   dest="gradAccum", metavar="K",
                   help="accumulate gradients over K microbatches per "
                        "optimizer step (default 1)")
    p.add_argument("--model-parallel", type=int, default=1,
                   dest="modelParallel", metavar="N",
                   help="shard large param/optimizer tensors over an "
                        "N-way 'model' mesh axis (must divide the device "
                        "count; default 1 = replicated)")
    p.add_argument("--seq-parallel", type=int, default=1,
                   dest="seqParallel", metavar="N",
                   help="N-way 'seq' mesh axis for --pipeline-parallel "
                        "+ --attention ring (ring attention inside each "
                        "pipeline stage; default 1 = 2-D mesh)")
    p.add_argument("--attention",
                   choices=("full", "ring", "flash", "ring_flash"),
                   default="full",
                   help="attention implementation for --model vit: XLA "
                        "softmax (default), sequence-parallel ring "
                        "attention over the 'model' mesh axis (requires "
                        "--model-parallel >= 2), the Pallas "
                        "flash-attention kernel (O(S) memory), or "
                        "ring_flash — the ring with the Pallas kernel "
                        "inside each shard")
    p.add_argument("--pipeline-microbatches", type=int, default=0,
                   dest="pipelineMicrobatches", metavar="M",
                   help="GPipe microbatches per step for "
                        "--pipeline-parallel (default 0 = one per "
                        "stage); larger M shrinks the pipeline bubble "
                        "(P-1)/(M+P-1); per-device batch must divide "
                        "by M")
    p.add_argument("--moe-experts", type=int, default=0,
                   dest="moeExperts", metavar="E",
                   help="replace the vit MLPs with E-expert switch "
                        "mixture-of-experts layers (expert-parallel "
                        "over the 'model' axis when --model-parallel "
                        ">= 2; default 0 = dense MLPs)")
    p.add_argument("--tensor-parallel", action="store_true",
                   dest="tensorParallel",
                   help="Megatron-style tensor parallelism for --model "
                        "vit: heads + MLP hidden sharded over the 'model' "
                        "mesh axis with sharded activations (requires "
                        "--model-parallel >= 2)")
    p.add_argument("--pipeline-parallel", action="store_true",
                   dest="pipelineParallel",
                   help="GPipe stage parallelism for --model vit: "
                        "transformer blocks sharded over the 'model' "
                        "mesh axis as pipeline stages (requires "
                        "--model-parallel >= 2)")


def build_parser() -> argparse.ArgumentParser:
    """CLI mirroring ref main.py:20-58: subcommands train/test."""
    parser = argparse.ArgumentParser(
        prog="main.py",
        description="TPU-native distributed classifier (JAX/XLA)")
    sub = parser.add_subparsers(dest="action", required=True,
                                help="action to execute")

    p_train = sub.add_parser("train", help="train model")
    _common_args(p_train)
    p_train.add_argument("-e", "--epochs", metavar="N", type=int,
                         dest="nbEpochs", default=NB_EPOCHS,
                         help=f"number of training epochs (default: {NB_EPOCHS})")
    p_train.add_argument("-f", "--file", metavar="file_path", type=str,
                         dest="checkpointFile", default=None,
                         help="training checkpoint file (resume)")

    p_test = sub.add_parser("test", help="test model")
    _common_args(p_test)
    p_test.add_argument("-f", "--file", metavar="file_path", type=str,
                        dest="checkpointFile", default=None, required=True,
                        help="model file")

    # Serving tier (serving/, ISSUE 15): batched, elastic inference
    # from a lineage-verified checkpoint.  Shares the full common flag
    # set — the serve path reuses the dataset spec (input shape /
    # normalization), model zoo, mesh, elastic and fault machinery.
    p_serve = sub.add_parser(
        "serve", help="serve a trained checkpoint: micro-batched "
                      "inference over HTTP with AOT-warmed batch "
                      "buckets, bounded-queue backpressure, and "
                      "elastic replica membership")
    _common_args(p_serve)
    p_serve.add_argument("-f", "--file", metavar="file_path", type=str,
                         dest="checkpointFile", default=None,
                         required=True,
                         help="checkpoint to serve (any params_layout; "
                              "converted at load)")
    p_serve.add_argument("--serve-port", type=int, default=8100,
                         dest="servePort", metavar="PORT",
                         help="HTTP port for this replica's /predict "
                              "(rank r binds PORT + r; default 8100)")
    p_serve.add_argument("--serve-buckets", type=str, default="1,4,16,64",
                         dest="serveBuckets", metavar="B1,B2,...",
                         help="batch-size buckets to AOT-compile; every "
                              "micro-batch pads to one of these "
                              "(default 1,4,16,64)")
    p_serve.add_argument("--serve-max-latency-ms", type=float,
                         default=20.0, dest="serveMaxLatencyMs",
                         metavar="MS",
                         help="micro-batcher flush deadline: a queued "
                              "request waits at most this long for "
                              "batch-mates (default 20)")
    p_serve.add_argument("--serve-queue", type=int, default=256,
                         dest="serveQueue", metavar="N",
                         help="bounded request-queue depth; past it "
                              "requests are shed with 503 (default 256)")
    p_serve.add_argument("--serve-request-timeout", type=float,
                         default=30.0, dest="serveRequestTimeout",
                         metavar="S",
                         help="per-request wait before the handler "
                              "answers 504 (default 30)")
    p_serve.add_argument("--serve-max-requests", type=int, default=0,
                         dest="serveMaxRequests", metavar="N",
                         help="stop after answering N requests "
                              "(0 = serve forever; gates use this)")

    # Offline aggregation — reads RSL_PATH/telemetry/rank*.jsonl written
    # by a --telemetry run; needs none of the train/test flags.
    p_rep = sub.add_parser(
        "telemetry", help="summarize a run's telemetry JSONL files")
    p_rep.add_argument("--rsl_path", type=str, default=RSL_PATH,
                       help=f"run directory holding telemetry/ "
                            f"(default: {RSL_PATH})")
    p_rep.add_argument("--json", action="store_true",
                       help="machine-readable aggregate output (the "
                            "same dict render_report formats)")

    # Offline goodput summary — reads RSL_PATH/goodput*.json written by
    # a run with --telemetry or --metrics-port; no train/test flags.
    p_gp = sub.add_parser(
        "goodput", help="summarize a run's goodput ledger: per-rank "
                        "wall-clock attribution by category, fleet "
                        "aggregate, and the top badput cause")
    p_gp.add_argument("--rsl_path", type=str, default=RSL_PATH,
                      help=f"run directory holding goodput*.json "
                           f"(default: {RSL_PATH})")

    # Offline timeline merge — reads RSL_PATH/telemetry/rank*.jsonl +
    # RSL_PATH/flightrec-rank*.json and writes Chrome trace-event JSON
    # (open in Perfetto / chrome://tracing); needs no train/test flags.
    p_tl = sub.add_parser(
        "timeline", help="merge per-rank telemetry + flight records "
                         "into a Perfetto-loadable Chrome trace, with "
                         "cross-rank skew + straggler attribution")
    p_tl.add_argument("--rsl_path", type=str, default=RSL_PATH,
                      help=f"run directory holding telemetry/ and "
                           f"flightrec dumps (default: {RSL_PATH})")
    p_tl.add_argument("-o", "--out", type=str, default=None,
                      metavar="FILE",
                      help="trace output path (default: "
                           "RSL_PATH/timeline.json)")

    # Offline roofline attribution — reads a jax.profiler trace dir
    # (RSL_PATH/trace from --profile, or an anomaly capture) plus
    # RSL_PATH/costs.json, writes RSL_PATH/roofline.json; no train/test
    # flags and no device work.
    p_rl = sub.add_parser(
        "roofline", help="per-op roofline attribution of a profiler "
                         "trace: time share, compute- vs memory-bound, "
                         "achieved-vs-roofline utilization")
    p_rl.add_argument("--rsl_path", type=str, default=RSL_PATH,
                      help=f"run directory holding trace/ and "
                           f"costs.json (default: {RSL_PATH})")
    p_rl.add_argument("--trace-dir", type=str, default=None,
                      metavar="DIR",
                      help="analyze this jax.profiler capture instead "
                           "of RSL_PATH/trace")
    p_rl.add_argument("--from-anomaly", action="store_true",
                      help="analyze the newest anomaly capture under "
                           "RSL_PATH/anomaly_traces/ instead")
    p_rl.add_argument("--top", type=int, default=20,
                      help="rows in the ranked table (default 20)")
    p_rl.add_argument("--json", action="store_true",
                      help="print the full roofline.json report "
                           "instead of the table")

    # Bench regression ledger — reads the checked-in BENCH_r*.json /
    # BENCH_SUITE.json history; exit 1 on a regression beyond the
    # threshold (see scripts/bench_trend.py).
    p_bt = sub.add_parser(
        "bench-trend", help="samples/s + MFU trajectory over the BENCH "
                            "history; deltas only between fresh rows; "
                            "exit 1 on regression")
    p_bt.add_argument("--dir", type=str, default=None, metavar="DIR",
                      help="directory holding BENCH_r*.json (default: "
                           "repo root)")
    p_bt.add_argument("--threshold", type=float, default=0.05,
                      help="fractional drop in the latest fresh-vs-"
                           "fresh delta that fails the run "
                           "(default 0.05)")
    p_bt.add_argument("--json", action="store_true",
                      help="machine-readable verdict output")

    # Fleet collector (fleet.py, ISSUE 16) — a standalone process, no
    # JAX backend: scrapes every rank exporter, merges the series
    # (counters by sum, latency sketches bucket-wise), re-exports them,
    # and turns --slo-spec objectives into incident bundles.
    p_fleet = sub.add_parser(
        "fleet", help="run the fleet metrics collector: scrape all "
                      "rank /metrics+/healthz exporters, merge into "
                      "fleet-level series (elastic-aware), re-export "
                      "/metrics + /fleet, evaluate --slo-spec "
                      "burn-rate objectives into incident bundles")
    p_fleet.add_argument("--rsl_path", type=str, default=RSL_PATH,
                         help=f"run directory shared with the serve "
                              f"world: fleet-metrics.jsonl and "
                              f"incident-*.json land here, trace "
                              f"records are mined from here "
                              f"(default: {RSL_PATH})")
    p_fleet.add_argument("--metrics-port", type=int, default=9100,
                         dest="metricsPort", metavar="PORT",
                         help="base port of the per-rank exporters to "
                              "scrape (rank r answers on PORT + r; "
                              "default 9100)")
    p_fleet.add_argument("--ranks", type=int, default=1,
                         dest="fleetRanks", metavar="N",
                         help="candidate rank count: ports PORT..PORT+"
                              "N-1 are probed every cycle, so elastic "
                              "joiners appear within one interval "
                              "(default 1)")
    p_fleet.add_argument("--fleet-port", type=int, default=9200,
                         dest="fleetPort", metavar="PORT",
                         help="serve the merged fleet /metrics (Prom "
                              "text) and /fleet (JSON) here "
                              "(default 9200; 0 disables re-export)")
    p_fleet.add_argument("--interval", type=float, default=1.0,
                         dest="fleetInterval", metavar="S",
                         help="scrape cycle period in seconds "
                              "(default 1.0)")
    p_fleet.add_argument("--stale-after", type=int, default=3,
                         dest="fleetStaleAfter", metavar="N",
                         help="consecutive failed scrapes before a "
                              "rank ages out of the merged series "
                              "(default 3)")
    p_fleet.add_argument("--max-cycles", type=int, default=0,
                         dest="fleetMaxCycles", metavar="N",
                         help="stop after N scrape cycles (0 = run "
                              "until interrupted; gates use N)")
    p_fleet.add_argument("--slo-spec", type=str, default=None,
                         dest="sloSpec", metavar="FILE",
                         help="JSON file declaring SLO objectives "
                              "(slo.py schema); firing objectives "
                              "write incident-*.json bundles")

    # Fleet front door (serving/frontdoor.py, ISSUE 19) — a standalone
    # control-plane process, no JAX backend: one client port, health-
    # aware routing over the serve replicas, SLO-driven autoscale,
    # canary rollout with automatic rollback.
    p_fd = sub.add_parser(
        "frontdoor", help="run the fleet front door: route client "
                          "/predict traffic across the serve replicas "
                          "with health-aware admission, autoscale on "
                          "queue/SLO pressure, and canary-roll out "
                          "newer lineage-verified checkpoints")
    p_fd.add_argument("--rsl_path", type=str, default=RSL_PATH,
                      help=f"run directory shared with the serve "
                           f"world: telemetry events and join logs "
                           f"land here (default: {RSL_PATH})")
    p_fd.add_argument("--port", type=int, default=8080,
                      dest="fdPort", metavar="PORT",
                      help="the one client-facing port (default 8080)")
    p_fd.add_argument("--ranks", type=int, default=1,
                      dest="fdRanks", metavar="N",
                      help="initial replica count: predict ports "
                           "serve-port..serve-port+N-1 (default 1)")
    p_fd.add_argument("--serve-port", type=int, default=8100,
                      dest="servePort", metavar="PORT",
                      help="base /predict port of the replicas "
                           "(replica i answers on PORT + i; "
                           "default 8100)")
    p_fd.add_argument("--metrics-port", type=int, default=0,
                      dest="metricsPort", metavar="PORT",
                      help="base port of the per-rank exporters: "
                           "health probes hit PORT + i /healthz and "
                           "the embedded fleet collector scrapes "
                           "them (0 = probe /livez on the predict "
                           "port instead, no collector; default 0)")
    p_fd.add_argument("--interval", type=float, default=0.5,
                      dest="fdInterval", metavar="S",
                      help="control-loop period: probe + scrape + "
                           "autoscale/rollout decisions (default 0.5)")
    p_fd.add_argument("--upstream-timeout", type=float, default=10.0,
                      dest="fdUpstreamTimeout", metavar="S",
                      help="per-attempt deadline on a proxied "
                           "/predict; a hung replica is cut off and "
                           "the request retried once on another "
                           "(default 10.0)")
    p_fd.add_argument("--pending-budget", type=int, default=64,
                      dest="fdPendingBudget", metavar="N",
                      help="fleet-wide in-flight request budget past "
                           "which admission sheds with 503 + "
                           "Retry-After (default 64)")
    p_fd.add_argument("--retry-after", type=float, default=1.0,
                      dest="fdRetryAfter", metavar="S",
                      help="Retry-After hint on shed responses "
                           "(default 1.0)")
    p_fd.add_argument("--eject-after", type=int, default=3,
                      dest="fdEjectAfter", metavar="N",
                      help="consecutive probe/transport failures "
                           "before a replica is ejected from routing "
                           "(readmitted on recovery; default 3)")
    p_fd.add_argument("--max-step-age", type=float, default=0.0,
                      dest="fdMaxStepAge", metavar="S",
                      help="eject a replica whose /healthz "
                           "last_step_age_s exceeds S (0 disables "
                           "the staleness check; default 0)")
    p_fd.add_argument("--max-cycles", type=int, default=0,
                      dest="fdMaxCycles", metavar="N",
                      help="stop after N control cycles (0 = run "
                           "until interrupted; gates use N)")
    p_fd.add_argument("--slo-spec", type=str, default=None,
                      dest="sloSpec", metavar="FILE",
                      help="SLO objectives (slo.py schema) evaluated "
                           "by the embedded collector; firing "
                           "verdicts are scale-up pressure")
    p_fd.add_argument("--stale-after", type=int, default=3,
                      dest="fleetStaleAfter", metavar="N",
                      help="collector scrapes before a silent rank "
                           "ages out of the merged series (default 3)")
    p_fd.add_argument("--autoscale", action="store_true",
                      dest="fdAutoscale",
                      help="enable the autoscale controller")
    p_fd.add_argument("--min-world", type=int, default=1,
                      dest="fdMinWorld", metavar="N",
                      help="never drain below N replicas; a world "
                           "below N is repaired by launching "
                           "(default 1)")
    p_fd.add_argument("--max-world", type=int, default=0,
                      dest="fdMaxWorld", metavar="N",
                      help="never launch above N replicas (0 = "
                           "--ranks; default 0)")
    p_fd.add_argument("--queue-high", type=float, default=8.0,
                      dest="fdQueueHigh", metavar="D",
                      help="scale up when every replica's queue depth "
                           "holds at/above D (default 8.0)")
    p_fd.add_argument("--queue-low", type=float, default=1.0,
                      dest="fdQueueLow", metavar="D",
                      help="scale down only when every queue depth "
                           "holds at/below D (default 1.0)")
    p_fd.add_argument("--up-hold", type=float, default=2.0,
                      dest="fdUpHold", metavar="S",
                      help="pressure must hold S seconds before a "
                           "scale-up (default 2.0)")
    p_fd.add_argument("--down-hold", type=float, default=10.0,
                      dest="fdDownHold", metavar="S",
                      help="calm must hold S seconds before a "
                           "scale-down (default 10.0)")
    p_fd.add_argument("--cooldown", type=float, default=5.0,
                      dest="fdCooldown", metavar="S",
                      help="minimum spacing between scale actions "
                           "(default 5.0)")
    p_fd.add_argument("--launch-cmd", type=str, default=None,
                      dest="fdLaunchCmd", metavar="CMD",
                      help="shell-ish command launched (Popen, no "
                           "shell) to add a replica on scale-up — "
                           "typically main.py serve --elastic-join")
    p_fd.add_argument("--rollout", action="store_true",
                      dest="fdRollout",
                      help="enable canary rollout of newer "
                           "lineage-verified checkpoints")
    p_fd.add_argument("--watch-dir", type=str, default=None,
                      dest="fdWatchDir", metavar="DIR",
                      help="directory whose ckpt-lineage.json is "
                           "watched for new checkpoints (default: "
                           "rsl_path)")
    p_fd.add_argument("--canary-fraction", type=float, default=0.34,
                      dest="fdCanaryFraction", metavar="F",
                      help="fraction of routable replicas given the "
                           "candidate (always >=1, never all; "
                           "default 0.34)")
    p_fd.add_argument("--canary-hold", type=float, default=5.0,
                      dest="fdCanaryHold", metavar="S",
                      help="canary soak time before promotion "
                           "(default 5.0)")
    p_fd.add_argument("--canary-min-requests", type=int, default=20,
                      dest="fdCanaryMinRequests", metavar="N",
                      help="canary answers required before a "
                           "promote/rollback verdict (default 20)")
    p_fd.add_argument("--canary-max-error", type=float, default=0.05,
                      dest="fdCanaryMaxError", metavar="R",
                      help="canary error ratio above which (and above "
                           "stable's) the candidate is rolled back "
                           "(default 0.05)")
    p_fd.add_argument("--canary-p95-factor", type=float, default=3.0,
                      dest="fdCanaryP95Factor", metavar="X",
                      help="roll back when canary p95 exceeds stable "
                           "p95 by this factor (default 3.0)")

    # Offline incident digest — reads RSL_PATH/incident-*.json written
    # by a fleet run; no flags beyond the run dir.
    p_inc = sub.add_parser(
        "incidents", help="report the SLO incident bundles a fleet "
                          "collector wrote for this run")
    p_inc.add_argument("--rsl_path", type=str, default=RSL_PATH,
                       help=f"run directory holding incident-*.json "
                            f"(default: {RSL_PATH})")

    # Deterministic fleet simulator (sim/) — virtual clock, no JAX
    # backend, no sockets; composes the real policy deciders at N=100+.
    p_sim = sub.add_parser(
        "sim", help="run a seeded fleet-scale scenario through the real "
                    "control-plane policies and emit live-run JSONL "
                    "artifacts (see scripts/sim_gate.py)")
    p_sim.add_argument("--rsl_path", type=str, default=RSL_PATH,
                       help=f"artifact output directory "
                            f"(default: {RSL_PATH})")
    p_sim.add_argument("--scenario", type=str, default="control",
                       dest="simScenario", metavar="NAME|PATH",
                       help="built-in scenario name (control, diurnal, "
                            "burst, preemption_wave, chaos) or a "
                            "scenario JSON path (default control)")
    p_sim.add_argument("--seed", type=int, default=0, dest="simSeed",
                       metavar="N",
                       help="simulation seed — same seed + same "
                            "scenario = byte-identical event log "
                            "(default 0)")
    p_sim.add_argument("--replicas", type=int, default=0,
                       dest="simReplicas", metavar="N",
                       help="fleet size override (0 = scenario default)")
    p_sim.add_argument("--duration", type=float, default=0.0,
                       dest="simDuration", metavar="S",
                       help="virtual-seconds override (0 = scenario "
                            "default)")
    p_sim.add_argument("--model", type=str, default=None,
                       dest="simModel", metavar="PATH",
                       help="latency-model JSON from "
                            "scripts/extract_latency_model.py (default: "
                            "built-in calibration)")

    # Static analysis (analysis/ graftlint) — no JAX backend touched.
    p_lint = sub.add_parser(
        "lint", help="run the graftlint static analysis pass "
                     "(exit 0 = clean; see scripts/graftlint.py)")
    p_lint.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: repo scope)")
    p_lint.add_argument("--json", action="store_true",
                        help="machine-readable findings output")
    p_lint.add_argument("--changed-only", action="store_true",
                        help="report findings only in git-changed "
                             "files; the whole program is still "
                             "analyzed so interprocedural rules stay "
                             "sound (whole-repo is the gate default)")
    p_lint.add_argument("--base", default="", metavar="REF",
                        help="with --changed-only: also include files "
                             "changed since REF (git diff REF...HEAD)")
    return parser


def config_from_argv(argv=None) -> Config:
    args = build_parser().parse_args(argv)
    if args.action == "telemetry":
        return Config(action="telemetry", rsl_path=args.rsl_path,
                      report_json=args.json)
    if args.action == "goodput":
        return Config(action="goodput", rsl_path=args.rsl_path)
    if args.action == "timeline":
        return Config(action="timeline", rsl_path=args.rsl_path,
                      timeline_out=args.out)
    if args.action == "roofline":
        return Config(action="roofline", rsl_path=args.rsl_path,
                      roofline_trace_dir=args.trace_dir,
                      roofline_from_anomaly=args.from_anomaly,
                      roofline_top=args.top, report_json=args.json)
    if args.action == "bench-trend":
        return Config(action="bench-trend", trend_dir=args.dir,
                      trend_threshold=args.threshold,
                      report_json=args.json)
    if args.action == "fleet":
        return Config(action="fleet", rsl_path=args.rsl_path,
                      metrics_port=args.metricsPort,
                      fleet_ranks=args.fleetRanks,
                      fleet_port=args.fleetPort,
                      fleet_interval=args.fleetInterval,
                      fleet_stale_after=args.fleetStaleAfter,
                      fleet_max_cycles=args.fleetMaxCycles,
                      slo_spec=args.sloSpec)
    if args.action == "frontdoor":
        return Config(action="frontdoor", rsl_path=args.rsl_path,
                      fd_port=args.fdPort,
                      fd_ranks=args.fdRanks,
                      serve_port=args.servePort,
                      metrics_port=args.metricsPort,
                      fd_interval=args.fdInterval,
                      fd_upstream_timeout=args.fdUpstreamTimeout,
                      fd_pending_budget=args.fdPendingBudget,
                      fd_retry_after=args.fdRetryAfter,
                      fd_eject_after=args.fdEjectAfter,
                      fd_max_step_age=args.fdMaxStepAge,
                      fd_max_cycles=args.fdMaxCycles,
                      slo_spec=args.sloSpec,
                      fleet_stale_after=args.fleetStaleAfter,
                      fd_autoscale=args.fdAutoscale,
                      fd_min_world=args.fdMinWorld,
                      fd_max_world=args.fdMaxWorld,
                      fd_queue_high=args.fdQueueHigh,
                      fd_queue_low=args.fdQueueLow,
                      fd_up_hold=args.fdUpHold,
                      fd_down_hold=args.fdDownHold,
                      fd_cooldown=args.fdCooldown,
                      fd_launch_cmd=args.fdLaunchCmd,
                      fd_rollout=args.fdRollout,
                      fd_watch_dir=args.fdWatchDir,
                      fd_canary_fraction=args.fdCanaryFraction,
                      fd_canary_hold=args.fdCanaryHold,
                      fd_canary_min_requests=args.fdCanaryMinRequests,
                      fd_canary_max_error=args.fdCanaryMaxError,
                      fd_canary_p95_factor=args.fdCanaryP95Factor)
    if args.action == "incidents":
        return Config(action="incidents", rsl_path=args.rsl_path)
    if args.action == "sim":
        return Config(action="sim", rsl_path=args.rsl_path,
                      sim_scenario=args.simScenario,
                      sim_seed=args.simSeed,
                      sim_replicas=args.simReplicas,
                      sim_duration=args.simDuration,
                      sim_model=args.simModel)
    if args.action == "lint":
        return Config(action="lint", lint_json=args.json,
                      lint_paths=tuple(args.paths),
                      lint_changed_only=args.changed_only,
                      lint_base=args.base)
    return Config(
        action=args.action,
        data_path=args.dataPath,
        rsl_path=args.rsl_path,
        dataset=args.dataset,
        model_name=args.modelName,
        optimizer=args.optimizer,
        loss=args.loss,
        batch_size=args.batchSize,
        nb_epochs=getattr(args, "nbEpochs", NB_EPOCHS),
        feature_extract=args.featureExtract,
        use_pretrained=args.usePretrained,
        pretrained_path=args.pretrainedPath,
        checkpoint_file=args.checkpointFile,
        debug=args.debug,
        half_precision=not args.no_bf16,
        precision=args.precision,
        remat=args.remat,
        scan_layers=args.scanLayers,
        data_mode=args.dataMode,
        prefetch=args.prefetch,
        producer_threads=args.producerThreads,
        device_prefetch=args.devicePrefetch,
        ckpt_async=args.ckptAsync,
        fault_plan=args.faultPlan,
        fault_seed=args.faultSeed,
        retry_max_attempts=args.retryMaxAttempts,
        retry_base_delay=args.retryBaseDelay,
        retry_timeout=args.retryTimeout,
        elastic=args.elastic,
        elastic_dir=args.elasticDir,
        health_timeout=args.healthTimeout,
        max_reconfigures=args.maxReconfigures,
        elastic_target=args.elasticTarget,
        elastic_min_world=args.elasticMinWorld,
        elastic_join=args.elasticJoin,
        elastic_join_wait=args.elasticJoinWait,
        keep_ckpts=args.keepCkpts,
        compilation_cache_dir=args.compilationCacheDir,
        no_compile_cache=args.noCompileCache,
        aot_warmup=args.aotWarmup,
        synthetic_fallback=args.syntheticFallback,
        profile=args.profile,
        telemetry=args.telemetry,
        epochs_per_dispatch=args.epochsPerDispatch,
        grad_accum=args.gradAccum,
        ckpt_format=args.ckptFormat,
        model_parallel=args.modelParallel,
        seq_parallel=args.seqParallel,
        attention=args.attention,
        tensor_parallel=args.tensorParallel,
        pipeline_parallel=args.pipelineParallel,
        pipeline_microbatches=args.pipelineMicrobatches,
        moe_experts=args.moeExperts,
        flightrec=args.flightrec,
        flightrec_ring=args.flightrecRing,
        metrics_port=args.metricsPort,
        anomaly_capture=args.anomalyCapture,
        anomaly_window=args.anomalyWindow,
        anomaly_mad_k=args.anomalyMadK,
        anomaly_min_excess=args.anomalyMinExcess,
        anomaly_capture_steps=args.anomalyCaptureSteps,
        anomaly_max_captures=args.anomalyMaxCaptures,
        # serve-only flags (defaults when action is train/test)
        serve_port=getattr(args, "servePort", 8100),
        serve_buckets=getattr(args, "serveBuckets", "1,4,16,64"),
        serve_max_latency_ms=getattr(args, "serveMaxLatencyMs", 20.0),
        serve_queue=getattr(args, "serveQueue", 256),
        serve_request_timeout=getattr(args, "serveRequestTimeout", 30.0),
        serve_max_requests=getattr(args, "serveMaxRequests", 0),
    )
