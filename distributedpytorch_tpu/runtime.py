"""L1: runtime — topology discovery, device mesh, multi-host init.

Replaces the reference's entire launcher machinery:

  * ``getLocalInterfaces`` ioctl NIC enumeration (ref: main.py:60-90) and the
    static ``DDTNodes`` IP/GPU table lookup in ``getDDTInfo``
    (ref: main.py:92-110): on TPU the runtime *is* the source of truth —
    ``jax.process_index()``, ``jax.process_count()``, ``jax.device_count()``.
  * ``torch.multiprocessing.spawn`` per-GPU fan-out (ref: main.py:133,135):
    JAX is SPMD within a process — one process drives all local chips; the
    mesh spans every chip in the slice.
  * ``init_process_group(backend='nccl', init_method='env://')`` rendezvous
    (ref: classif.py:86-87): ``jax.distributed.initialize()`` — coordinator
    discovery comes from the TPU runtime, no MASTER_ADDR/PORT to configure.

Logging/checkpoint gating uses the *global* process index (``is_main()``),
fixing SURVEY defect #7 (the reference gates on local rank ``gpu <= 0``,
ref classif.py:63,153,176, so every node's GPU-0 writes logs/checkpoints).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import compat  # noqa: F401  (jax version shims, PRNG config)
from . import faults

# Canonical mesh axis names.  Data parallelism ('data') is the reference's
# one and only strategy (SURVEY §2 parallelism checklist); 'model' exists so
# tensor-parallel shardings have a named axis to ride on; 'seq' is the
# third axis of the 3-D mesh the ring x pipeline composition uses
# (pipeline stages over 'model', ring sequence parallelism over 'seq').
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"

_initialized = False


def _multihost_env() -> bool:
    """True when the environment indicates a multi-host run.

    Covers both the explicit coordinator vars (set by launch tooling /
    ourselves) and the markers libtpu sets on Cloud TPU pod slices, where
    ``jax.distributed.initialize()`` auto-discovers the coordinator from
    TPU metadata without any vars of ours.  A plain single-host TPU VM sets
    none of these (or a single-entry hostname list), so the no-op single
    host path stays a no-op.
    """
    if any(v in os.environ for v in
           ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS")):
        return True
    # Cloud TPU pod markers: a multi-entry worker list means this process
    # is one of several hosts and MUST join the rendezvous.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h]) > 1


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           elastic: bool = False) -> None:
    """Multi-host rendezvous.  No-op on a single host.

    TPU equivalent of ref classif.py:86-87 (init_process_group) + the env-var
    plumbing at ref main.py:128-131.  On TPU pods the coordinator is
    discovered from the environment automatically (see ``_multihost_env``);
    args are an escape hatch for manual clusters (the moral equivalent of
    the reference's DDTNodes table, but optional) — and the path the
    multi-process CPU test drives.

    ``elastic=True`` (--elastic runs) stands the runtime up via
    ``elastic.manual_init`` instead of ``jax.distributed.initialize``:
    the stock client terminates the PROCESS from a C++ callback when
    the coordination service declares a peer dead (heartbeat timeout),
    which would kill the survivors the elastic path exists to save.
    The manual recipe disables that declaration so peer death is only
    ever discovered where it is survivable (collective error / bounded
    health agreement); it requires an explicit coordinator/world (args
    or env), matching how elastic jobs are launched.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is not None or _multihost_env():
        # env:// rendezvous parity (ref classif.py:86-87 reads MASTER_ADDR/
        # MASTER_PORT + explicit world_size/rank): fill what the caller
        # left None from the standard coordinator env vars, so a launcher
        # that only exports env — the reference's whole contract — works
        # argless.  On real TPU pods none of the *_NUM_PROCESSES/_PROCESS_ID
        # vars are set and everything stays None, preserving
        # jax.distributed.initialize()'s cluster auto-detection.
        if coordinator_address is None:
            coordinator_address = (
                os.environ.get("JAX_COORDINATOR_ADDRESS")
                or os.environ.get("COORDINATOR_ADDRESS"))
        if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
            num_processes = int(os.environ["JAX_NUM_PROCESSES"])
        if process_id is None and "JAX_PROCESS_ID" in os.environ:
            process_id = int(os.environ["JAX_PROCESS_ID"])
        # Cross-process collectives on the CPU backend need gloo (the
        # multi-process test path; TPU runs ignore this — platform
        # selection happens later and TPU collectives ride ICI/DCN).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older/newer jax without the option
            pass
        def _init():
            # The rendezvous is the canonical transient failure (the
            # coordinator not up yet, a blipped tunnel): retried under
            # the process retry policy.  RuntimeError is how
            # jax.distributed surfaces a failed/timed-out rendezvous.
            faults.fire("runtime.init")
            if elastic and coordinator_address is not None \
                    and num_processes is not None \
                    and process_id is not None:
                from . import elastic as elastic_mod

                elastic_mod.manual_init(coordinator_address,
                                        num_processes, process_id)
            else:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id)

        faults.retry(_init, "runtime.init",
                     transient=(OSError, TimeoutError, RuntimeError))
    _initialized = True


def join_distributed(elastic_dir: str,
                     timeout_s: Optional[float] = None) -> dict:
    """Enter an already-running ``--elastic`` world as a joiner.

    The grow-side counterpart of ``initialize_distributed``: instead of
    standing up a world from coordinator/num_processes/process_id, this
    process drops a join claim in the shared rendezvous dir, waits for
    the running world's coordinator to admit it at a health boundary,
    and connects at the rank the admit marker assigns
    (elastic.join_world; the connect itself runs under fault site
    ``elastic.grow_reinit``).  Returns the join info dict — the caller
    emits the telemetry, since the joiner's rank is only known now.
    """
    global _initialized
    if _initialized:
        raise RuntimeError("join_distributed: the distributed runtime "
                           "is already initialized in this process")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older/newer jax without the option
        pass
    from . import elastic as elastic_mod

    info = elastic_mod.join_world(elastic_dir, timeout_s)
    _initialized = True
    return info


def process_index() -> int:
    """Global rank of this host process (ref: firstLocalRank+gpu, classif.py:82)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main() -> bool:
    """Gate for logging/checkpointing — global, fixing SURVEY defect #7."""
    return jax.process_index() == 0


def local_devices() -> Sequence[jax.Device]:
    return jax.local_devices()


def world_size() -> int:
    """Total chip count across the slice (ref: worldSize, main.py:100-108)."""
    return jax.device_count()


def make_mesh(data_parallel: Optional[int] = None,
              model_parallel: int = 1, seq_parallel: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the device mesh the SPMD train step runs over.

    Default: 1-D mesh over every chip on the 'data' axis — the TPU-native
    equivalent of the reference's world of DDP ranks.  ``model_parallel > 1``
    folds the same devices into a 2-D (data, model) mesh; XLA lays the 'data'
    axis over ICI so gradient reductions ride the fast interconnect.
    ``seq_parallel > 1`` adds the third 'seq' axis (ring x pipeline:
    stages on 'model', the attention ring on 'seq').
    """
    devs = np.array(devices if devices is not None else jax.devices())
    n = devs.size
    if model_parallel < 1 or seq_parallel < 1 \
            or n % (model_parallel * seq_parallel):
        raise ValueError(
            f"model_parallel={model_parallel} * seq_parallel={seq_parallel}"
            f" must divide device count {n}")
    dp = (data_parallel if data_parallel is not None
          else n // (model_parallel * seq_parallel))
    if dp * model_parallel * seq_parallel != n:
        raise ValueError(
            f"data_parallel({dp}) * model_parallel({model_parallel}) * "
            f"seq_parallel({seq_parallel}) != {n}")
    if seq_parallel > 1:
        return Mesh(devs.reshape(dp, model_parallel, seq_parallel),
                    (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))
    return Mesh(devs.reshape(dp, model_parallel), (DATA_AXIS, MODEL_AXIS))


def make_serve_mesh() -> Mesh:
    """The serving replica's mesh: THIS process's devices only.

    Request serving shards at the REQUEST level — each replica answers
    its own HTTP port from its own device set — so the predict program
    must contain no cross-host collectives: a replica's dispatch
    cadence stays its own, and a peer dying mid-batch cannot wedge a
    survivor inside XLA.  The shared jax.distributed world still exists
    underneath for membership (elastic health agreement, join
    rendezvous); it just never appears in the inference mesh.
    """
    return make_mesh(devices=jax.local_devices())


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch arrays: sharded along the leading axis over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Params / opt state: fully replicated (pure data parallelism)."""
    return NamedSharding(mesh, P())


def barrier() -> None:
    """Block until every process reaches this point (no-op single host).

    Used around multi-writer filesystem operations (orbax checkpoint
    swap): a delete racing another host's writes corrupts the checkpoint.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dpt_barrier")


def any_process(flag: bool) -> bool:
    """True when ANY process's flag is set — one tiny allgather.

    Used for decisions every host must take at the SAME loop boundary
    (e.g. preemption shutdown): without agreement, one host could break out
    of the training loop while the rest enter the next epoch's collective
    and deadlock waiting for it.  Single-process: no communication.
    """
    if jax.process_count() == 1:
        return flag
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.array([flag]))
    return bool(np.any(flags))


def agree_health(failed: bool, shutdown: bool,
                 timeout_s: Optional[float] = None,
                 grow: bool = False) -> tuple:
    """(any_failed, any_shutdown, any_grow) across every process — ONE
    allgather.

    The failure-agreement extension of ``any_process``: a rank that hit
    a fatal error at a loop boundary reports ``failed=True`` here
    instead of raising straight out of the loop, so its peers learn of
    the failure through a collective they ALL reach (in the same
    program order) rather than hanging forever in the dead rank's next
    epoch collective.  The caller then re-raises locally on the failed
    rank and raises ``faults.PeerFailureError`` on the healthy ones —
    every rank exits cleanly, same boundary, nonzero.

    ``timeout_s`` (--health-timeout) bounds the agreement itself: the
    allgather only completes when EVERY peer reaches the boundary, so a
    rank that died between boundaries (SIGKILL, OOM, preemption without
    grace) would otherwise hang the survivors right here — the one
    collective that was supposed to detect failure.  With a timeout the
    allgather runs on a daemon thread; if it hasn't completed in time
    the local verdict is ``faults.HealthTimeoutError`` and the caller
    decides (reconfigure under --elastic, loud exit otherwise).  The
    abandoned thread is left to the runtime teardown — Python offers no
    safe preemption, and the gloo transport either errors it out
    promptly or the process is about to exit/reinit anyway.

    ``grow`` is the elastic scale-UP vote: a rank that saw an
    admissible join claim in the rendezvous dir reports it here, so
    every survivor agrees to reconfigure into the larger world at the
    SAME boundary — the same agreement discipline that keeps failure
    exits aligned.  Filesystem polling is racy across ranks (one rank
    can list the claim before its peers); the OR over the allgather is
    exactly the repair: one vote is enough, and the rendezvous
    coordinator re-checks the claims authoritatively.

    Folding all three flags into one message keeps the collective
    schedule identical to the old single-flag health check (no extra
    rendezvous per boundary).  Single-process: no communication.
    """
    if jax.process_count() == 1:
        return bool(failed), bool(shutdown), bool(grow)
    from jax.experimental import multihost_utils

    def _gather():
        return multihost_utils.process_allgather(
            np.array([failed, shutdown, grow], dtype=bool))

    if timeout_s is None or timeout_s <= 0:
        flags = _gather()
    else:
        box: dict = {}

        def _run():
            try:
                box["flags"] = _gather()
            except BaseException as e:  # surfaced on the caller thread
                box["error"] = e

        t = threading.Thread(target=_run, daemon=True,
                             name="agree_health")
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            raise faults.HealthTimeoutError(
                f"health agreement did not complete within {timeout_s}s"
                " — a peer died or wedged before reaching the boundary")
        if "error" in box:
            raise box["error"]
        flags = box["flags"]
    return (bool(np.any(flags[..., 0])), bool(np.any(flags[..., 1])),
            bool(np.any(flags[..., 2])))


_cache_hits = 0
_cache_listener_installed = False


def _on_monitoring_event(name: str, **kwargs) -> None:
    global _cache_hits
    if name == "/jax/compilation_cache/cache_hits":
        _cache_hits += 1


def compilation_cache_hits() -> int:
    """Persistent-compilation-cache hits observed in this process (via
    jax.monitoring).  Consumers snapshot before a compile and diff after
    — e.g. the --aot-warmup compile/cache_hit telemetry gauge."""
    return _cache_hits


def donation_safe() -> bool:
    """False when jitted programs must NOT use ``donate_argnums``.

    On the CPU backend, an executable served from the persistent
    compilation cache comes back with broken input-output aliasing
    metadata: the first donated dispatch is fine, but feeding its output
    back in as the next donated input reuses freed buffers — NaN params
    or a segfault, observed exactly on resume (a fresh process whose
    every compile is a disk-cache hit).  TPU/GPU executable
    serialization round-trips aliasing correctly, and CPU without the
    cache is fine, so donation is disabled only for the one broken
    combination.  Donation on CPU is a memory optimization, never a
    correctness requirement, so dropping it is free.
    """
    if jax.default_backend() != "cpu":
        return True
    return getattr(jax.config, "jax_compilation_cache_dir", None) is None


def configure_compilation_cache(cache_dir: Optional[str]) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Wires ``jax_compilation_cache_dir`` plus the two thresholds that
    would otherwise silently skip this framework's programs (the default
    1 s compile-time floor excludes exactly the small per-step programs
    compiled most often), and installs the cache-hit monitoring listener.
    ``None`` disables the cache (--no-compile-cache).  Call
    ``reset_compilation_cache`` when the run is over — the config is
    process-global and the dir may be a temporary run directory.
    """
    global _cache_listener_installed
    if cache_dir is None:
        reset_compilation_cache()
        return
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                     ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(opt, val)
        except Exception:  # older jax without the knob
            pass
    # The cache object is initialized once per process from the config —
    # reset so THIS dir takes effect even if an earlier run set another.
    _reset_cache_state()
    if not _cache_listener_installed:
        try:
            from jax import monitoring

            monitoring.register_event_listener(_on_monitoring_event)
            _cache_listener_installed = True
        except (ImportError, AttributeError):
            # jax without jax.monitoring: the compile/cache_hit gauge is
            # simply unavailable; caching itself still works
            pass


def reset_compilation_cache() -> None:
    """Detach the persistent cache (end of run / tests): later compiles
    must not keep writing into a possibly-deleted run directory."""
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # jax without the option: nothing to detach
        pass
    _reset_cache_state()


def _reset_cache_state() -> None:
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        # private-API best effort: a jax that moved/renamed it keeps the
        # old cache object alive, which is safe (stale dir, not wrong
        # results)
        pass


_sanction_local = threading.local()


def host_transfer_sanctioned() -> bool:
    """True while the CURRENT THREAD is inside a
    ``sanctioned_host_transfer()`` block — read by the transfer-guard
    sanitizer's patched sync primitives (analysis/transfer_guard.py)."""
    return getattr(_sanction_local, "depth", 0) > 0


@contextlib.contextmanager
def sanctioned_host_transfer():
    """Context marking a device->host transfer as a sanctioned sync point.

    The training loop's contract is per-EPOCH syncing: the only blocking
    ``device_get``s are the epoch-end metric fetches and the checkpoint
    snapshot.  Those sites wrap themselves in this context; the
    transfer-guard sanitizer (analysis/transfer_guard.py) then runs a
    smoke epoch with device->host transfers *disallowed* globally, so
    any OTHER transfer — a per-step ``.item()``, a stray ``float()`` on
    a device value, the reference's own bug class — fails the smoke
    instead of silently serializing the hot path.

    Two layers compose here: a thread-local sanction marker the
    sanitizer's patched primitives consult (effective on every backend,
    including CPU where jax's native guard sees no "transfer" at all),
    and jax's own ``transfer_guard_device_to_host('allow')`` scope so
    the native guard agrees on TPU/GPU.  Outside the sanitizer this is
    free: the marker is a thread-local increment and the native scope
    re-allows what the default config already allows.
    """
    _sanction_local.depth = getattr(_sanction_local, "depth", 0) + 1
    guard = getattr(jax, "transfer_guard_device_to_host", None)
    try:
        if guard is None:  # very old jax without transfer guards
            yield
        else:
            with guard("allow"):
                yield
    finally:
        _sanction_local.depth -= 1


def device_memory_limit() -> Optional[int]:
    """Per-device accelerator memory in bytes, or None when unknown.

    TPU/GPU backends report ``bytes_limit`` via ``Device.memory_stats()``;
    the CPU backend (and some virtual-device setups) report nothing — then
    residency decisions fall back to the configured byte cap alone.
    """
    limits = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            # backend-specific call: CPU/virtual devices raise various
            # types; "unknown" is the documented answer either way
            return None
        if not stats or "bytes_limit" not in stats:
            return None
        limits.append(int(stats["bytes_limit"]))
    return min(limits) if limits else None


def check_devices() -> bool:
    """Describe the accelerator topology (ref: checkCuda, utils.py:168-180).

    Returns True when an accelerator (TPU/GPU) backend is active, False for
    CPU — callers may use this the way the reference used its CUDA flag.
    """
    devs = jax.devices()
    backend = devs[0].platform if devs else "none"
    logging.info(f"JAX {jax.__version__}")
    logging.info(f"backend: {backend}, {len(devs)} device(s): "
                 f"{[d.device_kind for d in devs]}")
    logging.info(f"processes: {jax.process_count()}, "
                 f"local devices: {len(jax.local_devices())}")
    return backend not in ("cpu",)
