"""Per-op roofline attribution from ``jax.profiler`` traces (ISSUE 12).

The observability spine before this module answered *how much* time a
run spent (goodput ledger), *what* a program costs in aggregate
(costs.py), and *which pipeline stage* is slow (profile_breakdown.py) —
but not which *op* eats the step, nor whether that op is compute- or
memory-bound.  This module closes the loop:

1. parse a profiler trace directory (the Perfetto/trace-event dump that
   ``--profile``, ``--aot-warmup`` profiling, and the anomaly detector's
   captures all produce — including on CPU) into per-op time
   attribution,
2. join each op against analytic FLOPs/bytes derived from the saved HLO
   text in ``costs.json`` (``costs.hlo_op_costs``), falling back to
   name heuristics when no cost metadata exists,
3. classify each op compute-bound vs memory-bound against the device
   roofline (ops/flops peak tables; a generic ridge when the device is
   unknown) and compute achieved-vs-ceiling utilization,
4. emit a ranked top-K table with an explicit "unattributed residual"
   line, persist ``RSL_PATH/roofline.json`` atomically, and record a
   ``roofline`` telemetry event so the timeline merge can annotate
   ranks with their op-level blame.

Parsing notes (verified against jax 0.4.37 CPU traces): per-op events
are ``ph: "X"`` slices carrying ``args.hlo_op``/``args.hlo_module``;
runtime envelope events (``ThunkExecutor::Execute``,
``TfrtCpuExecutable::Execute``) on the same threads NEST and DUPLICATE,
so durations must never be summed — every aggregate here is an interval
*union* per thread, which dedups nesting for free and excludes
inter-step idle gaps from the step-time denominator.
"""

from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from . import costs
from .ops.flops import peak_flops, peak_membw

SCHEMA = 1

# Ridge point (FLOPs/byte) used when the device peaks are unknown (CPU,
# future TPU kinds).  ~10 is where contemporary chips of every class
# (server CPUs, GPUs, TPUs) put the knee within a small factor; the
# report labels the source "generic" so nobody mistakes the resulting
# bound classes for a measured roofline.
DEFAULT_RIDGE = 10.0

# Substrings that mark an op as MXU work when no analytic costs exist.
_COMPUTE_NAME_HINTS = ("dot", "conv", "gemm", "matmul", "einsum")


def find_trace_files(trace_dir: str) -> List[str]:
    """Every ``*.trace.json[.gz]`` under ``trace_dir``, recursively.

    jax nests its output as ``plugins/profile/<timestamp>/<host>...`` —
    callers pass the directory they handed to ``start_trace`` and this
    finds whatever landed underneath.
    """
    hits: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(glob.escape(trace_dir), pat),
                              recursive=True))
    return sorted(hits)


def _load_trace(path: str) -> Optional[dict]:
    """One trace file -> parsed JSON; None (caller warns) when torn."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8",
                           errors="replace") as f:
                return json.load(f)
        with open(path, encoding="utf-8", errors="replace") as f:
            return json.load(f)
    except (OSError, ValueError, EOFError):
        return None


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _self_times(hlo_events: List[tuple]) -> List[tuple]:
    """Exclusive (self) time of each nested slice on one thread.

    Profiler op slices NEST: a ``while`` op's event covers every body
    op executed inside it, so summing durations would double-count the
    whole loop.  The standard flame-graph sweep attributes each
    microsecond to the innermost op: self = dur - sum(direct children).
    Input: ``(ts, end, dur, opkey)`` tuples; output: ``(opkey,
    self_us)`` per event.
    """
    evs = sorted(hlo_events, key=lambda e: (e[0], -e[1]))
    out: List[tuple] = []
    stack: List[list] = []  # [end, child_us, opkey, dur]
    eps = 1e-6
    for ts, end, dur, opkey in evs:
        while stack and ts >= stack[-1][0] - eps:
            top = stack.pop()
            out.append((top[2], max(0.0, top[3] - top[1])))
        if stack:
            stack[-1][1] += dur
        stack.append([end, 0.0, opkey, dur])
    while stack:
        top = stack.pop()
        out.append((top[2], max(0.0, top[3] - top[1])))
    return out


def parse_trace_dir(trace_dir: str) -> Dict[str, Any]:
    """Aggregate a trace directory into per-op time attribution.

    Returns ``{ops, step_time_us, attributed_us, residual_us, coverage,
    n_trace_files, n_events, warnings}`` where ``ops`` maps
    ``(module, op_name)`` -> ``{time_us, count}`` with time_us the op's
    exclusive (self) time — nested slices (a ``while`` covering its
    body) attribute each microsecond to the innermost op.

    Step time is the wall-clock union of all *device-thread* activity:
    a thread counts as a device executor when the majority of its
    active time lies inside ``hlo_op`` slices (the XLA CPU Eigen/client
    threads, TPU core tracks), which excludes the python dispatch
    thread whose epoch-long host work would otherwise swamp the
    denominator.  Intervals are
    merged ACROSS threads per file, so a client thread blocking on a
    compute thread counts the wall second once, not twice.
    """
    files = find_trace_files(trace_dir)
    if not files:
        raise ValueError(
            f"no profiler trace files (*.trace.json[.gz]) under "
            f"{trace_dir!r}; run with --profile or point --trace-dir at "
            f"a jax.profiler capture")
    warnings: List[str] = []
    n_events = 0
    n_parsed = 0
    # per file: thread key -> (all X intervals, hlo (ts, end, dur, opkey))
    file_threads: List[Dict[Tuple[Any, Any], Tuple[list, list]]] = []
    for path in files:
        data = _load_trace(path)
        if not isinstance(data, dict) or not isinstance(
                data.get("traceEvents"), list):
            warnings.append(f"torn or unparseable trace file skipped: "
                            f"{os.path.basename(path)}")
            continue
        n_parsed += 1
        threads: Dict[Tuple[Any, Any], Tuple[list, list]] = {}
        for ev in data["traceEvents"]:
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            try:
                ts = float(ev["ts"])
                dur = float(ev.get("dur", 0.0))
            except (KeyError, TypeError, ValueError):
                continue
            if dur < 0:
                continue
            n_events += 1
            key = (ev.get("pid"), ev.get("tid"))
            allx, hlox = threads.setdefault(key, ([], []))
            allx.append((ts, ts + dur))
            args = ev.get("args")
            op = args.get("hlo_op") if isinstance(args, dict) else None
            if not op:
                continue
            module = args.get("hlo_module") or "?"
            hlox.append((ts, ts + dur, dur, (str(module), str(op))))
        file_threads.append(threads)
    if n_parsed == 0:
        raise ValueError(
            f"all {len(files)} trace file(s) under {trace_dir!r} were "
            f"torn or unparseable")

    def _aggregate(strict: bool):
        ops: Dict[Tuple[str, str], Dict[str, float]] = {}
        step_us = attr_us = 0.0
        for threads in file_threads:
            step_iv: List[Tuple[float, float]] = []
            attr_iv: List[Tuple[float, float]] = []
            for allx, hlox in threads.values():
                if not hlox:
                    continue
                # Device-executor test: most of the thread's active
                # time is op execution.  Time-based (not slice-count)
                # because tiny programs interleave a few ops with many
                # short listener/envelope slices, while the python
                # dispatch thread carries huge host slices and
                # near-zero op time.
                if strict:
                    hlo_u = _union_us([iv[:2] for iv in hlox])
                    if 2 * hlo_u < _union_us(list(allx)):
                        continue
                step_iv.extend(allx)
                attr_iv.extend(iv[:2] for iv in hlox)
                for opkey, self_us in _self_times(hlox):
                    agg = ops.setdefault(opkey,
                                         {"time_us": 0.0, "count": 0})
                    agg["time_us"] += self_us
                    agg["count"] += 1
            step_us += _union_us(step_iv)
            attr_us += _union_us(attr_iv)
        return ops, step_us, attr_us

    ops, step_time_us, attributed_us = _aggregate(strict=True)
    if not ops:
        # Single-threaded/inline execution (XLA:CPU under a tiny
        # program) interleaves the few op slices with host dispatch on
        # ONE thread, so no thread passes the majority test.  Fall back
        # to any thread carrying op slices — the host slices stay in
        # the step-time denominator, so coverage remains honest.
        ops, step_time_us, attributed_us = _aggregate(strict=False)
        if ops:
            warnings.append(
                "no dedicated device-executor thread found; including "
                "host-dispatch threads in step time (inline execution)")
    if not ops:
        raise ValueError(
            f"trace under {trace_dir!r} has no per-op (hlo_op) events — "
            f"nothing executed on a device thread while tracing")
    residual_us = max(0.0, step_time_us - attributed_us)
    coverage = attributed_us / step_time_us if step_time_us > 0 else 0.0
    return {"ops": ops, "step_time_us": step_time_us,
            "attributed_us": attributed_us, "residual_us": residual_us,
            "coverage": coverage, "n_trace_files": n_parsed,
            "n_events": n_events, "warnings": warnings}


# -- cost join + classification ----------------------------------------


def _op_cost_maps(costs_data: Optional[dict]) -> Dict[str, Dict[str, dict]]:
    """costs.json -> {program_name: {op_name: {flops, bytes, ...}}} for
    every program that saved its HLO text."""
    maps: Dict[str, Dict[str, dict]] = {}
    if not costs_data:
        return maps
    for prog, entry in (costs_data.get("programs") or {}).items():
        hlo = entry.get("hlo") if isinstance(entry, dict) else None
        if isinstance(hlo, str) and hlo:
            try:
                maps[prog] = costs.hlo_op_costs(hlo)
            except Exception as e:  # parser is best-effort by contract
                logging.warning(f"roofline: HLO parse failed for "
                                f"program {prog!r}: {e}")
    return maps


def _program_for_module(module: str, maps: Dict[str, Dict[str, dict]]
                        ) -> Optional[Dict[str, dict]]:
    """Trace module name -> per-op cost map.  XLA names modules
    ``jit_<fn>`` after the jitted callable; costs.py keys programs by
    the framework's own names (train_epoch, ...), so try exact, then
    the jit_-stripped form, then a unique substring match."""
    if module in maps:
        return maps[module]
    stripped = module[4:] if module.startswith("jit_") else module
    if stripped in maps:
        return maps[stripped]
    hits = [m for name, m in maps.items()
            if name in stripped or stripped in name]
    return hits[0] if len(hits) == 1 else None


def bound_class(flops: Optional[float], bytes_: Optional[float],
                device_kind: Optional[str] = None,
                dtype: Optional[str] = None,
                name: str = "") -> Dict[str, Any]:
    """The shared classifier primitive: compute- vs memory-bound from
    arithmetic intensity against the device ridge (generic ridge when
    the device peaks are unknown), degrading to a name heuristic when
    no analytic FLOPs/bytes exist.  Used per-op here and per-stage by
    scripts/profile_breakdown.py, so both report the same physics."""
    peak_b = peak_membw(device_kind)
    peak_f = peak_flops(device_kind, dtype) if device_kind and dtype \
        else None
    if peak_f and peak_b:
        ridge, ridge_source = peak_f / peak_b, "device"
    else:
        ridge, ridge_source = DEFAULT_RIDGE, "generic"
    ai = (flops / bytes_) if flops is not None and bytes_ else None
    if ai is not None:
        bound = "compute" if ai >= ridge else "memory"
        class_source = "analytic"
    else:
        lname = name.lower()
        bound = "compute" if any(h in lname for h in
                                 _COMPUTE_NAME_HINTS) else "memory"
        class_source = "heuristic"
    return {"arithmetic_intensity": ai, "bound": bound,
            "class_source": class_source,
            "ridge_flops_per_byte": ridge, "ridge_source": ridge_source,
            "_peak_f": peak_f, "_peak_b": peak_b}


def classify(parsed: Dict[str, Any], device_kind: Optional[str],
             costs_data: Optional[dict]) -> Dict[str, Any]:
    """Join parsed op times against analytic costs and classify each op
    against the roofline.  Pure data-in/data-out; returns the full
    report dict (sans persistence stamps)."""
    maps = _op_cost_maps(costs_data)
    step_us = parsed["step_time_us"]
    rows: List[Dict[str, Any]] = []
    for (module, name), agg in parsed["ops"].items():
        cost = None
        prog_map = _program_for_module(module, maps)
        if prog_map:
            cost = prog_map.get(name)
        flops = bytes_ = dtype = opcode = None
        if cost:
            flops = cost.get("flops")
            bytes_ = cost.get("bytes")
            dtype = cost.get("dtype")
            opcode = cost.get("opcode")
        cls = bound_class(flops, bytes_, device_kind, dtype, name)
        ai = cls["arithmetic_intensity"]
        peak_f, peak_b = cls.pop("_peak_f"), cls.pop("_peak_b")
        time_s = agg["time_us"] * 1e-6
        achieved = (flops * agg["count"] / time_s) \
            if flops and time_s > 0 else None
        ceiling = ceiling_source = None
        if ai is not None and peak_f and peak_b:
            ceiling = min(peak_f, ai * peak_b)
            ceiling_source = "device"
        rows.append({
            "name": name, "module": module, "opcode": opcode,
            "time_us": agg["time_us"],
            "time_share": agg["time_us"] / step_us if step_us else 0.0,
            "count": agg["count"], "flops": flops, "bytes": bytes_,
            "dtype": dtype, **cls,
            "achieved_flops_per_s": achieved,
            "roofline_ceiling_flops_per_s": ceiling,
            "ceiling_source": ceiling_source, "utilization": None,
        })
    # Device peaks unknown (CPU): the best observed FLOP rate in THIS
    # trace becomes the ceiling, so utilization still ranks ops by
    # headroom — labeled "empirical" to keep it honest.
    empirical = max((r["achieved_flops_per_s"] for r in rows
                     if r["achieved_flops_per_s"]), default=None)
    for r in rows:
        if r["achieved_flops_per_s"] is None:
            continue
        if r["roofline_ceiling_flops_per_s"] is None and empirical:
            r["roofline_ceiling_flops_per_s"] = empirical
            r["ceiling_source"] = "empirical"
        if r["roofline_ceiling_flops_per_s"]:
            r["utilization"] = (r["achieved_flops_per_s"]
                                / r["roofline_ceiling_flops_per_s"])
    rows.sort(key=lambda r: -r["time_us"])
    return {
        "schema": SCHEMA,
        "device_kind": device_kind,
        "step_time_us": step_us,
        "attributed_us": parsed["attributed_us"],
        "residual_us": parsed["residual_us"],
        "coverage": parsed["coverage"],
        "n_trace_files": parsed["n_trace_files"],
        "n_events": parsed["n_events"],
        "n_ops": len(rows),
        "warnings": parsed["warnings"],
        "ops": rows,
    }


def analyze(trace_dir: str, rsl_path: Optional[str] = None,
            costs_data: Optional[dict] = None,
            device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Parse + join + classify one trace directory.

    ``costs_data`` defaults to ``RSL_PATH/costs.json`` when an rsl_path
    is given; ``device_kind`` defaults to what that file recorded at
    save time (the device the trace actually ran on, unlike the device
    this analysis runs on).
    """
    parsed = parse_trace_dir(trace_dir)
    if costs_data is None and rsl_path:
        costs_data = costs.load(rsl_path)
    if device_kind is None and costs_data:
        device_kind = costs_data.get("device_kind")
    report = classify(parsed, device_kind, costs_data)
    report["trace_dir"] = trace_dir
    report["generated_at"] = time.time()
    if costs_data is None:
        report["warnings"] = report["warnings"] + [
            "no costs.json found: bound classes are name heuristics "
            "and utilization is unavailable"]
    return report


# -- persistence + rendering -------------------------------------------


def save_report(report: Dict[str, Any], rsl_path: str) -> str:
    """Atomic write to ``RSL_PATH/roofline.json``; returns the path."""
    os.makedirs(rsl_path, exist_ok=True)
    path = os.path.join(rsl_path, "roofline.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True, default=float)
        f.write("\n")
    os.replace(tmp, path)
    return path


def emit_telemetry(report: Dict[str, Any], tel: Any, top: int = 3) -> None:
    """Record a ``roofline`` telemetry event summarizing the analysis —
    the hook the timeline merge reads for per-rank annotations."""
    tel.event(
        "roofline",
        coverage=round(report["coverage"], 4),
        step_time_us=round(report["step_time_us"], 1),
        residual_us=round(report["residual_us"], 1),
        n_ops=report["n_ops"],
        device_kind=report.get("device_kind"),
        top_ops=top_ops(report, top),
    )


def top_ops(report: Dict[str, Any], k: int = 3) -> List[Dict[str, Any]]:
    """Compact top-k rows (name/share/bound/utilization) for embedding
    in bench rows, telemetry events, and timeline annotations."""
    out = []
    for r in report["ops"][:k]:
        out.append({"name": r["name"],
                    "time_share": round(r["time_share"], 4),
                    "bound": r["bound"],
                    "utilization": (round(r["utilization"], 4)
                                    if r["utilization"] is not None
                                    else None)})
    return out


def _fmt_rate(v: Optional[float]) -> str:
    if not v:
        return "-"
    for exp, unit in ((12, "T"), (9, "G"), (6, "M"), (3, "K")):
        if v >= 10 ** exp:
            return f"{v / 10 ** exp:.1f}{unit}"
    return f"{v:.0f}"


def render_report(report: Dict[str, Any], top: int = 20) -> str:
    """Human-readable ranked table + the unattributed-residual line."""
    lines = ["== roofline attribution =="]
    dk = report.get("device_kind") or "unknown device"
    lines.append(
        f"trace: {report.get('trace_dir', '?')} "
        f"({report['n_trace_files']} file(s), {report['n_events']} events)")
    ridge = report["ops"][0]["ridge_flops_per_byte"] if report["ops"] \
        else DEFAULT_RIDGE
    src = report["ops"][0]["ridge_source"] if report["ops"] else "generic"
    lines.append(f"device: {dk}  ridge: {ridge:.1f} FLOPs/byte ({src})")
    anom = report.get("anomaly")
    if isinstance(anom, dict):
        trig = (anom.get("trigger") or {}).get("trigger", "?")
        lines.append(f"anomaly capture {anom.get('capture', '?')}: "
                     f"trigger {trig} at epoch {anom.get('epoch', '?')} "
                     f"step {anom.get('step', '?')}")
    lines.append(
        f"step time {report['step_time_us'] / 1e3:.2f} ms — "
        f"{report['coverage'] * 100:.1f}% attributed to "
        f"{report['n_ops']} named ops")
    header = (f"  {'op':<40} {'time':>9} {'share':>6} {'count':>6} "
              f"{'bound':>7} {'AI':>8} {'FLOP/s':>8} {'util':>6}")
    lines.append(header)
    for r in report["ops"][:top]:
        ai = f"{r['arithmetic_intensity']:.2f}" \
            if r["arithmetic_intensity"] is not None else "-"
        util = f"{r['utilization'] * 100:.1f}%" \
            if r["utilization"] is not None else "-"
        mark = "" if r["class_source"] == "analytic" else "?"
        name = r["name"] if len(r["name"]) <= 40 else r["name"][:37] + "..."
        lines.append(
            f"  {name:<40} {r['time_us'] / 1e3:>7.2f}ms "
            f"{r['time_share'] * 100:>5.1f}% {r['count']:>6} "
            f"{r['bound'] + mark:>7} {ai:>8} "
            f"{_fmt_rate(r['achieved_flops_per_s']):>8} {util:>6}")
    if len(report["ops"]) > top:
        rest = report["ops"][top:]
        rest_us = sum(r["time_us"] for r in rest)
        lines.append(f"  ... {len(rest)} more ops, "
                     f"{rest_us / 1e3:.2f} ms combined")
    lines.append(
        f"  unattributed residual: {report['residual_us'] / 1e3:.2f} ms "
        f"({(1 - report['coverage']) * 100:.1f}% of step time) — "
        f"runtime gaps between op executions")
    if any(r["class_source"] == "heuristic" for r in report["ops"]):
        lines.append("  (? = bound class from op-name heuristic; no "
                     "analytic FLOPs/bytes for that op)")
    for w in report["warnings"]:
        lines.append(f"  warning: {w}")
    return "\n".join(lines)


# -- anomaly-capture integration ---------------------------------------


def anomaly_capture_dirs(rsl_path: str) -> List[str]:
    """Anomaly capture directories (flightrec's ``capture-<n>``) that
    actually contain trace files, newest capture number last."""
    root = os.path.join(rsl_path, "anomaly_traces")
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    def _num(n: str) -> int:
        try:
            return int(n.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return -1
    for name in sorted(names, key=_num):
        path = os.path.join(root, name)
        if name.startswith("capture-") and os.path.isdir(path) \
                and find_trace_files(path):
            out.append(path)
    return out


# -- CLI ---------------------------------------------------------------


def run_cli(rsl_path: str, trace_dir: Optional[str] = None,
            from_anomaly: bool = False, top: int = 20,
            as_json: bool = False, emit_events: bool = True) -> str:
    """``main.py roofline`` entry: analyze, persist, report.

    Default trace source is ``RSL_PATH/trace`` (what ``--profile``
    writes); ``--from-anomaly`` analyzes the newest anomaly capture
    instead; an explicit ``--trace-dir`` wins over both.  Raises
    ValueError with an actionable message when there is nothing to
    analyze (CLI prints it and exits 1, repo convention).
    """
    if trace_dir is None:
        if from_anomaly:
            dirs = anomaly_capture_dirs(rsl_path)
            if not dirs:
                raise ValueError(
                    f"no anomaly captures with trace files under "
                    f"{os.path.join(rsl_path, 'anomaly_traces')!r}; "
                    f"run with --anomaly-profile first")
            trace_dir = dirs[-1]
        else:
            trace_dir = os.path.join(rsl_path, "trace")
    report = analyze(trace_dir, rsl_path=rsl_path)
    # Anomaly captures are self-describing (flightrec writes a
    # manifest.json with the trigger verdict beside the raw trace):
    # carry the why next to the op-level blame.
    try:
        with open(os.path.join(trace_dir, "manifest.json")) as f:
            report["anomaly"] = json.load(f)
    except (OSError, ValueError):
        pass
    path = save_report(report, rsl_path)
    if emit_events:
        from . import telemetry
        tel = telemetry.Telemetry(enabled=True, rsl_path=rsl_path, rank=0)
        try:
            emit_telemetry(report, tel)
        finally:
            tel.close()
    if as_json:
        return json.dumps(report, indent=2, sort_keys=True, default=float)
    return render_report(report, top=top) + f"\n(saved to {path})"
