"""Explicit mixed-precision policy (SURVEY §4 numerics).

Replaces the single ``half_precision: bool`` that used to pick one dtype for
everything.  A :class:`PrecisionPolicy` names four dtypes with distinct jobs:

* ``param_dtype``   — storage dtype of the master parameters the optimizer
  updates.  f32 masters + half-precision compute is the classic recipe
  (Micikevicius et al., "Mixed Precision Training"): the tiny per-step
  update would underflow if applied to a bf16/f16 copy.
* ``compute_dtype`` — dtype of forward/backward matmuls.  Flax modules cast
  params to this at apply time (``promote_dtype``), so the MXU runs
  half-precision without ever storing a half master.
* ``accum_dtype``   — dtype of every cross-step / cross-microbatch
  accumulator: loss and metric sums, batch-norm running stats, and the
  gradient buffer under ``--grad-accum``.  Always f32 in the shipped
  presets; the ``mixed-precision-accum`` graftlint rule enforces that new
  code keeps it that way.
* ``output_dtype``  — dtype logits are cast to before the loss.  f32 so the
  softmax/log-sum-exp runs at full precision regardless of compute dtype.

TPU bf16 keeps the f32 exponent range, so the bf16 presets need no loss
scaling.  The ``f16`` preset (non-TPU backends only) enables the dynamic
loss-scaling state machine below, with overflow-skip and periodic growth.

The reference trains pure f32 and has no precision knobs at all; this whole
module is a framework divergence-by-addition, anchored to the ROADMAP "close
the MFU gap" item.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One named mixed-precision configuration.

    ``loss_scale`` is the *initial* dynamic loss scale; 0.0 disables scaling
    entirely (the bf16/f32 presets).  ``loss_scale_growth`` is the number of
    consecutive finite steps after which the scale doubles.
    """

    name: str
    param_dtype: Any
    compute_dtype: Any
    accum_dtype: Any
    output_dtype: Any
    loss_scale: float = 0.0
    loss_scale_growth: int = 2000

    @property
    def scales_loss(self) -> bool:
        return self.loss_scale > 0.0

    def describe(self) -> dict:
        """JSON-able summary, recorded in telemetry as ``precision_policy``."""
        return {
            "preset": self.name,
            "param_dtype": jnp.dtype(self.param_dtype).name,
            "compute_dtype": jnp.dtype(self.compute_dtype).name,
            "accum_dtype": jnp.dtype(self.accum_dtype).name,
            "output_dtype": jnp.dtype(self.output_dtype).name,
            "loss_scale": float(self.loss_scale),
        }


# Preset table.  "bf16" formalizes what the repo always did implicitly:
# flax keeps f32 params (param_dtype defaults to f32) and casts to the
# module ``dtype`` at apply time, losses cast logits to f32 before the
# log-sum-exp, and BN running stats live in f32.  "bf16_full" additionally
# stores bf16 masters (halves param + optimizer-state memory; small-model
# use only — updates below ~2^-8 of a weight's magnitude are lost).
PRESETS = {
    "f32": PrecisionPolicy(
        name="f32",
        param_dtype=jnp.float32, compute_dtype=jnp.float32,
        accum_dtype=jnp.float32, output_dtype=jnp.float32,
    ),
    "bf16": PrecisionPolicy(
        name="bf16",
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
        accum_dtype=jnp.float32, output_dtype=jnp.float32,
    ),
    "bf16_full": PrecisionPolicy(
        name="bf16_full",
        param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
        accum_dtype=jnp.float32, output_dtype=jnp.float32,
    ),
    # f16 has a 5-bit exponent: gradients underflow without scaling.  TPUs
    # have no f16 MXU path, so this preset is rejected on TPU backends
    # (cli validation) — it exists for GPU/CPU parity experiments.
    "f16": PrecisionPolicy(
        name="f16",
        param_dtype=jnp.float32, compute_dtype=jnp.float16,
        accum_dtype=jnp.float32, output_dtype=jnp.float32,
        loss_scale=float(2 ** 15),
    ),
}

PRESET_NAMES = tuple(PRESETS)


def get_policy(name: str) -> PrecisionPolicy:
    try:
        return PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown precision preset {name!r}; choose from {PRESET_NAMES}"
        ) from None


def from_flags(precision: Optional[str], half_precision: bool) -> PrecisionPolicy:
    """Resolve the CLI/Config pair into a policy.

    ``--precision`` wins when given; otherwise the legacy ``half_precision``
    bool maps to the preset that reproduces its historical behavior exactly
    (True → "bf16", False → "f32").
    """
    if precision is not None:
        return get_policy(precision)
    return PRESETS["bf16" if half_precision else "f32"]


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast floating-point leaves of a pytree to ``dtype``; leave ints alone."""
    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(_cast, tree)


class LossScaleState(flax.struct.PyTreeNode):
    """Dynamic loss-scale carried inside TrainState (f16 preset only).

    ``scale`` multiplies the loss before backward (gradients come out
    scaled, the step divides them back).  ``good_steps`` counts consecutive
    finite steps; at ``growth_interval`` the scale doubles.  A non-finite
    gradient halves the scale and the parameter/optimizer update is skipped
    (``jnp.where`` select, so the step stays one compiled program).
    """

    scale: jax.Array
    good_steps: jax.Array

    @classmethod
    def create(cls, initial_scale: float) -> "LossScaleState":
        return cls(scale=jnp.asarray(initial_scale, jnp.float32),
                   good_steps=jnp.asarray(0, jnp.int32))

    def adjust(self, grads_finite: jax.Array,
               growth_interval: int = 2000) -> "LossScaleState":
        grew = self.good_steps + 1 >= growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grew, self.scale * 2.0, self.scale),
            jnp.maximum(self.scale * 0.5, 1.0),
        )
        # Cap so a long run of clean steps cannot push the scale to inf.
        new_scale = jnp.minimum(new_scale, jnp.asarray(2.0 ** 24, jnp.float32))
        new_good = jnp.where(grads_finite & ~grew, self.good_steps + 1, 0)
        return self.replace(scale=new_scale, good_steps=new_good)


def all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every element of every leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    checks = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    return jnp.stack(checks).all()


def tree_select(pred: jax.Array, on_true: Any, on_false: Any) -> Any:
    """Elementwise pytree select — used for the overflow-skip update."""
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f), on_true, on_false
    )
