"""Sharded sampling with torch DistributedSampler parity semantics.

Replaces ref dataloader.py:147-152 (DistributedSampler for train/valid/test).
Semantics preserved:

  * one *global* epoch-keyed permutation, identical on every process
    (generator seeded with seed+epoch, the torch rule the reference relies
    on via sampler.set_epoch — with the off-by-one of SURVEY defect #8
    fixed: the epoch is keyed *before* the epoch runs);
  * pad-to-divisible by wraparound so every rank sees the same number of
    samples (torch: indices += indices[:padding]);
  * rank r takes the strided slice indices[r::world].

One addition for TPU static shapes: the epoch is further padded up to a
whole number of *batches*, and a validity mask marks wraparound duplicates
so metrics can ignore them (the reference instead lets the last batch be
ragged, which XLA would recompile for — and its shuffled, shard-local test
metrics silently double-count; see SURVEY defect #9).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..utils import epoch_numpy_rng


@dataclass
class ShardedSampler:
    num_samples: int          # dataset size N
    world_size: int           # total replicas (chips)
    rank: int                 # this replica's global index
    batch_size: int           # per-replica batch
    shuffle: bool = True
    seed: int = 0
    drop_last: bool = False

    def __post_init__(self):
        if not (0 <= self.rank < self.world_size):
            raise ValueError(f"rank {self.rank} outside world "
                             f"{self.world_size}")
        per_rank = self.num_samples / self.world_size
        if self.drop_last:
            self.batches_per_epoch = int(per_rank // self.batch_size)
        else:
            self.batches_per_epoch = max(
                1, math.ceil(per_rank / self.batch_size))
        self.samples_per_rank = self.batches_per_epoch * self.batch_size

    def __len__(self) -> int:
        return self.batches_per_epoch

    def global_permutation(self, epoch: int) -> np.ndarray:
        """The all-ranks-agree permutation, padded by wraparound."""
        if self.shuffle:
            perm = epoch_numpy_rng(self.seed, epoch).permutation(
                self.num_samples)
        else:
            perm = np.arange(self.num_samples)
        total = self.samples_per_rank * self.world_size
        if total <= self.num_samples:
            return perm[:total]
        reps = math.ceil(total / self.num_samples)
        return np.tile(perm, reps)[:total]

    def epoch_indices(self, epoch: int) -> tuple[np.ndarray, np.ndarray]:
        """(indices, valid) for this rank: each (batches_per_epoch, B).

        ``valid`` is False exactly on wraparound-padding positions, so
        globally every real sample is counted once per epoch (when N is not
        world*B-divisible the tail duplicates are masked, not dropped).
        """
        perm = self.global_permutation(epoch)
        total = perm.size
        flat_valid = np.ones(total, dtype=bool)
        if total > self.num_samples:
            flat_valid[self.num_samples:] = False
        mine = perm[self.rank::self.world_size]
        mine_valid = flat_valid[self.rank::self.world_size]
        return (mine.reshape(self.batches_per_epoch, self.batch_size),
                mine_valid.reshape(self.batches_per_epoch, self.batch_size))
