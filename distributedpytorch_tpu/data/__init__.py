"""L2: data pipeline (TPU-native replacement for ref dataloader.py).

The reference pipeline is: torchvision dataset -> per-sample host transforms
in NUM_WORKERS loader processes -> DistributedSampler shard -> pinned-memory
H2D copy (ref dataloader.py:89-170).  On TPU (and with augmentation fused
into the jitted step) the pipeline collapses to:

  raw uint8 arrays on host  ->  epoch-keyed global permutation (sampler.py)
  ->  contiguous gather of this process's shard  ->  sharded device_put
  ->  on-device augment/normalize inside the compiled step (augment.py).
"""

from .datasets import Dataset, load_dataset
from .sampler import ShardedSampler
from .pipeline import ShardedLoader

__all__ = ["Dataset", "load_dataset", "ShardedSampler", "ShardedLoader"]
