"""Raw dataset readers: MNIST/FashionMNIST IDX, CIFAR-10 pickle, synthetic.

The reference delegates to torchvision.datasets (ref dataloader.py:92,118-126)
with download=False — i.e. it *reads the standard on-disk formats* and never
actually downloads (``downloadDataset`` at ref dataloader.py:85-87 is dead
code).  We read the same formats directly with numpy: IDX for (Fashion)MNIST
and the python pickle batches for CIFAR-10.  A deterministic synthetic
generator provides a drop-in corpus for tests/benchmarks on machines without
the real files.
"""

from __future__ import annotations

import gzip
import logging
import os
import pickle
import struct
from typing import Tuple

import numpy as np

from .. import faults

# torchvision layout: <root>/MNIST/raw/<file> (what the reference's
# download=False load expects); we also accept the files directly in root.
_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _find_idx_file(root: str, subdir: str, fname: str) -> str:
    for cand in (
        os.path.join(root, subdir, "raw", fname),
        os.path.join(root, subdir, fname),
        os.path.join(root, "raw", fname),
        os.path.join(root, fname),
    ):
        if os.path.exists(cand) or os.path.exists(cand + ".gz"):
            return cand
    raise FileNotFoundError(
        f"{fname}[.gz] not found under {root} (looked in {subdir}/raw, "
        f"{subdir}, raw/, and the root itself)")


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (the MNIST wire format)."""
    with _open_maybe_gz(path) as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        if dtype_code != 0x08:  # uint8 — the only type (Fashion)MNIST uses
            raise ValueError(f"{path}: unsupported IDX dtype {dtype_code:#x}")
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(shape)


def load_mnist_like(root: str, subdir: str
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(train_images (N,28,28) u8, train_labels, test_images, test_labels)."""
    tr_x = read_idx(_find_idx_file(root, subdir, _MNIST_FILES["train_images"]))
    tr_y = read_idx(_find_idx_file(root, subdir, _MNIST_FILES["train_labels"]))
    te_x = read_idx(_find_idx_file(root, subdir, _MNIST_FILES["test_images"]))
    te_y = read_idx(_find_idx_file(root, subdir, _MNIST_FILES["test_labels"]))
    return tr_x, tr_y.astype(np.int32), te_x, te_y.astype(np.int32)


def load_cifar10(root: str
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """CIFAR-10 python batches -> (N,32,32,3) u8 HWC arrays."""
    base = None
    for cand in (os.path.join(root, "cifar-10-batches-py"), root):
        if os.path.exists(os.path.join(cand, "data_batch_1")):
            base = cand
            break
    if base is None:
        raise FileNotFoundError(
            f"cifar-10-batches-py/data_batch_1 not found under {root}")

    def _read(name):
        with open(os.path.join(base, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(d[b"labels"], dtype=np.int32)
        return np.ascontiguousarray(x), y

    xs, ys = zip(*[_read(f"data_batch_{i}") for i in range(1, 6)])
    te_x, te_y = _read("test_batch")
    return np.concatenate(xs), np.concatenate(ys), te_x, te_y


def make_synthetic(num_train: int = 60000, num_test: int = 10000,
                   image_size: int = 28, channels: int = 1,
                   num_classes: int = 10, seed: int = 0,
                   class_sep: float = 1.0, noise: float = 32.0
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic learnable MNIST-shaped corpus.

    Each class has a fixed smooth prototype pattern; samples are the
    prototype plus noise and a random brightness jitter, so a small CNN can
    fit it quickly — giving tests/benchmarks a real learning signal without
    shipping the actual MNIST files.

    ``class_sep`` < 1 shrinks every prototype toward the all-class mean
    (raising inter-class overlap) and ``noise`` raises the per-pixel
    sigma — together they make the Bayes error nonzero, which is what the
    accuracy-parity harness needs: at the defaults a 2-epoch CNN saturates
    at 100% and equal-at-ceiling accuracies carry no information (round-2
    verdict); SYNTH_HARD below is tuned so the same CNN lands mid-range,
    where a real learning-dynamics divergence between the two frameworks
    would show up as an accuracy gap.
    """
    rng = np.random.default_rng(seed)
    # Smooth per-class prototypes: low-frequency random fields, upsampled.
    low = rng.normal(size=(num_classes, 7, 7, channels))
    protos = low.repeat(image_size // 7 + 1, axis=1)[:, :image_size]
    protos = protos.repeat(image_size // 7 + 1, axis=2)[:, :, :image_size]
    protos = (protos - protos.min()) / (np.ptp(protos) + 1e-8)
    if class_sep != 1.0:
        mean_proto = protos.mean(axis=0, keepdims=True)
        protos = mean_proto + class_sep * (protos - mean_proto)

    def _split(n, split_seed):
        r = np.random.default_rng(split_seed)
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        x = protos[y] * 255.0
        x = x * r.uniform(0.6, 1.0, size=(n, 1, 1, 1))
        x = x + r.normal(0, noise, size=x.shape)
        x = np.clip(x, 0, 255).astype(np.uint8)
        if channels == 1:
            x = x[..., 0]
        return x, y

    tr_x, tr_y = _split(num_train, seed + 1)
    te_x, te_y = _split(num_test, seed + 2)
    return tr_x, tr_y, te_x, te_y


# The non-saturating variant the accuracy-parity harness trains on
# (--dataset synthetic_hard): tuned so the reference recipe (2-epoch CNN,
# batch 64, Adam 1e-3) lands mid-range instead of at the 100% ceiling.
SYNTH_HARD = {"class_sep": 0.45, "noise": 70.0}


def load_raw(dataset: str, data_path: str, synthetic_fallback: bool = False):
    """Dispatch by dataset name.

    A real dataset whose raw files are absent is an error (surfaced as
    ValueError so the CLI log-and-exits, ref classif.py:119-120 style) —
    unless ``synthetic_fallback`` opts into the deterministic synthetic
    corpus (with a loud warning); accuracy numbers are then meaningless for
    the real dataset.

    Transient read failures (a flaky network filesystem — or the
    data.read fault site) are retried under the process retry policy;
    FileNotFoundError is NOT retried (a missing corpus never becomes
    present by waiting) and keeps its fallback semantics.
    """

    def _dispatch():
        faults.fire("data.read")
        if dataset == "mnist":
            return load_mnist_like(data_path, "MNIST")
        if dataset == "fashion_mnist":
            return load_mnist_like(data_path, "FashionMNIST")
        if dataset == "cifar10":
            return load_cifar10(data_path)
        return None

    try:
        out = faults.retry(
            _dispatch, "data.read",
            transient=(PermissionError, InterruptedError,
                       faults.InjectedIOError, TimeoutError))
        if out is not None:
            return out
    except FileNotFoundError as e:
        if not synthetic_fallback:
            raise ValueError(
                f"{dataset} raw files not found under {data_path!r} ({e}); "
                "pass --synthetic-fallback to train on the synthetic corpus "
                "instead") from e
        logging.warning(f"{dataset} raw files not found ({e}); "
                        "FALLING BACK TO SYNTHETIC DATA — accuracy numbers "
                        "will not reflect the real dataset")
        dataset = "synthetic"
    if dataset == "synthetic":
        return make_synthetic()
    if dataset == "synthetic_hard":
        return make_synthetic(**SYNTH_HARD)
    raise ValueError(f"unknown dataset {dataset!r}")
