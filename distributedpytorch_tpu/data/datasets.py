"""Dataset container: load, normalize-stats scan, split, debug subset.

Mirrors the reference's MNIST class responsibilities (ref dataloader.py:47-135)
minus iteration (pipeline.py) and transforms (augment.py, on device):

  * mean/std computed from raw train pixels exactly as the reference does:
    ``data.float().mean()/255`` over all pixels (ref dataloader.py:92-96) —
    scalar stats applied to every channel;
  * 90/10 train/valid split (VALID_RATIO=0.9, ref dataloader.py:23,129-133)
    via a seed-deterministic permutation (the torch ``random_split`` drew
    from the globally-seeded generator; same role here, explicit seed);
  * valid split uses eval transforms (ref dataloader.py:134-135);
  * --debug truncates train to 200 samples (ref dataloader.py:139-144) —
    and actually works from the CLI flag (the reference's DEBUG rebind never
    reached spawned children, SURVEY §5 config wart).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict

import numpy as np

from . import io
from ..config import VALID_RATIO, DEBUG_SUBSET


@dataclass
class Split:
    images: np.ndarray   # uint8 (N,H,W) grayscale or (N,H,W,3) rgb
    labels: np.ndarray   # int32 (N,)

    def __len__(self) -> int:
        return self.labels.shape[0]


@dataclass
class Dataset:
    name: str
    splits: Dict[str, Split]
    mean: float
    std: float
    nb_classes: int = 10

    @property
    def channels(self) -> int:
        img = self.splits["train"].images
        return 1 if img.ndim == 3 else img.shape[-1]

    def class_weights(self) -> np.ndarray:
        """Inverse-frequency weights for weighted CE / focal loss.

        The reference *reads* ``dataset.data['train'].classWeights``
        (ref classif.py:112-117) but never defines it, so those loss paths
        crash (SURVEY defect #4).  This is the fixed implementation:
        w_c = N / (num_classes * count_c), the standard balanced weighting.
        """
        counts = np.bincount(self.splits["train"].labels,
                             minlength=self.nb_classes).astype(np.float64)
        counts = np.maximum(counts, 1.0)
        w = len(self.splits["train"]) / (self.nb_classes * counts)
        return w.astype(np.float32)


def load_dataset(name: str, data_path: str, seed: int,
                 debug: bool = False, log: bool = False,
                 synthetic_fallback: bool = False) -> Dataset:
    tr_x, tr_y, te_x, te_y = io.load_raw(name, data_path,
                                         synthetic_fallback)

    # Normalization stats from raw train pixels (ref dataloader.py:92-96).
    mean = float(tr_x.astype(np.float32).mean() / 255.0)
    std = float(tr_x.astype(np.float32).std() / 255.0)

    # 90/10 train/valid split, deterministic (ref dataloader.py:129-133).
    n = tr_y.shape[0]
    n_train = int(n * VALID_RATIO)
    perm = np.random.default_rng(seed).permutation(n)
    tr_idx, va_idx = perm[:n_train], perm[n_train:]

    if debug:
        # ref dataloader.py:139-144 truncates train to 200; the valid/test
        # truncations the reference left commented out are enabled here so
        # --debug is a true smoke mode (divergence documented in README).
        tr_idx = tr_idx[:DEBUG_SUBSET]
        va_idx = va_idx[:DEBUG_SUBSET]
        te_x, te_y = te_x[:DEBUG_SUBSET], te_y[:DEBUG_SUBSET]

    ds = Dataset(
        name=name,
        splits={
            "train": Split(tr_x[tr_idx], tr_y[tr_idx]),
            "valid": Split(tr_x[va_idx], tr_y[va_idx]),
            "test": Split(te_x, te_y),
        },
        mean=mean,
        std=std,
        nb_classes=int(max(tr_y.max(), te_y.max())) + 1,
    )
    if log:  # ref dataloader.py:69-72
        logging.info(f"Number of training examples: {len(ds.splits['train'])}")
        logging.info(f"Number of validation examples: {len(ds.splits['valid'])}")
        logging.info(f"Number of testing examples: {len(ds.splits['test'])}")
    return ds
