"""On-device augmentation: the reference's transform pipeline as one fused
batched affine warp, jit-compiled onto the TPU.

Reference pipeline (ref dataloader.py:101-116), executed per-sample on host
CPU in NUM_WORKERS loader processes:

  train: RandomRotation(5, fill=0) -> RandomResizedCrop(dataDim)
         -> ToTensor -> TensorRepeat(3) -> Normalize(mean, std)
  eval:  Resize(dataDim) -> CenterCrop(dataDim)
         -> ToTensor -> TensorRepeat(3) -> Normalize(mean, std)

TPU-native redesign: rotation and random-resized-crop are both affine maps,
so they compose into a *single* inverse-affine bilinear sample per image —
one pass over the pixels, batched with vmap, running on device inside the
same XLA program as the forward/backward step.  ToTensor/repeat/normalize
fuse into the same kernel for free.  This removes the host-side transform
bottleneck entirely (the image never exists at dataDim resolution on host).

Parity notes vs torchvision:
  * RandomResizedCrop samples scale∈(0.08,1.0), log-uniform ratio∈(3/4,4/3)
    like torchvision, but clamps the crop box into bounds instead of the
    10-attempt rejection loop + center-crop fallback (rejection is
    jit-hostile; the sampled distributions differ only in rare tail cases).
  * Rotation angle ~ U(-5°,5°), fill 0, about the image center — same.
  * Interpolation: torchvision RandomRotation defaults to NEAREST and the
    crop resize is bilinear; the fused warp is bilinear end-to-end, a
    per-pixel numeric divergence from the reference train transform
    (deliberate: one exact bilinear pass, better quality, MXU-friendly).
  * All randomness flows from a single per-step JAX key: one batched
    ``uniform(key, (b, 5))`` draw, indexed by position in the
    deterministically-composed global batch (see _sample_affine_batch), so
    results are independent of device count and identical between the
    resident and streaming loaders.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp

SCALE_RANGE = (0.08, 1.0)        # torchvision RandomResizedCrop defaults
# math.log, not jnp.log: module-level jnp would initialize a JAX backend at
# import time, which breaks hosts that must pick the platform *after* import.
LOG_RATIO_RANGE = (math.log(3.0 / 4.0), math.log(4.0 / 3.0))
MAX_ROTATION_DEG = 5.0           # ref dataloader.py:102


def _sample_affine_batch(key: jax.Array, b: int, h: int, w: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array, jax.Array]:
    """Sample (theta, crop_y0, crop_x0, crop_h, crop_w), each (b,).

    ONE threefry invocation for the whole batch: a single
    ``uniform(key, (b, 5))`` replaces per-image fold_in/split/draw chains
    (7 batched threefry calls).  Measured v5e step time is unchanged within
    noise — XLA overlapped the PRNG work anyway — so this is kept as a
    simplification, not a speedup.  Draws are keyed by position in the
    (deterministically-composed) global batch, so results remain
    independent of device count and identical between resident and
    streaming loaders.
    """
    u = jax.random.uniform(key, (b, 5))
    theta = (2.0 * u[:, 0] - 1.0) * MAX_ROTATION_DEG * (jnp.pi / 180.0)
    scale = SCALE_RANGE[0] + u[:, 1] * (SCALE_RANGE[1] - SCALE_RANGE[0])
    ratio = jnp.exp(LOG_RATIO_RANGE[0]
                    + u[:, 2] * (LOG_RATIO_RANGE[1] - LOG_RATIO_RANGE[0]))
    area = scale * h * w
    crop_w = jnp.clip(jnp.sqrt(area * ratio), 1.0, float(w))
    crop_h = jnp.clip(jnp.sqrt(area / ratio), 1.0, float(h))
    y0 = u[:, 3] * (h - crop_h)
    x0 = u[:, 4] * (w - crop_w)
    return theta, y0, x0, crop_h, crop_w


def _warp_one(img: jax.Array, theta: jax.Array, y0: jax.Array,
              x0: jax.Array, crop_h: jax.Array, crop_w: jax.Array,
              out_dim: int) -> jax.Array:
    """Inverse-affine bilinear sample of one (H,W) image -> (out,out).

    Output pixel (i,j) -> crop-box coords in the rotated frame -> rotate by
    -theta about the image center -> source coords in the original image.
    Outside-of-image samples read 0 (RandomRotation's fill, ref :102).

    MXU-native formulation: bilinear sampling is expressed with hat-weight
    matrices instead of gathers —

        out[p] = sum_y hat(src_y[p]-y) * sum_x hat(src_x[p]-x) * img[y,x]
               = (Ay * (Ax @ img^T))[p] summed over y

    which is EXACT bilinear interpolation (each hat has <=2 nonzeros) and
    compiles to two small matmuls per image.  jax.scipy.ndimage
    map_coordinates lowers to per-pixel gathers that run ~10x slower on
    TPU (measured: 2.7ms vs 0.25ms per 64-image step on v5e).
    """
    h, w = img.shape

    ii = jnp.arange(out_dim, dtype=jnp.float32)
    # Half-pixel-centered resize convention (matches bilinear resize).
    ys = y0 + (ii[:, None] + 0.5) * crop_h / out_dim - 0.5
    xs = x0 + (ii[None, :] + 0.5) * crop_w / out_dim - 0.5
    ys = jnp.broadcast_to(ys, (out_dim, out_dim))
    xs = jnp.broadcast_to(xs, (out_dim, out_dim))

    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    cos_t, sin_t = jnp.cos(-theta), jnp.sin(-theta)
    src_y = (cos_t * (ys - cy) - sin_t * (xs - cx) + cy).reshape(-1)
    src_x = (sin_t * (ys - cy) + cos_t * (xs - cx) + cx).reshape(-1)

    a_y = jnp.maximum(0.0, 1.0 - jnp.abs(
        src_y[:, None] - jnp.arange(h, dtype=jnp.float32)[None, :]))
    a_x = jnp.maximum(0.0, 1.0 - jnp.abs(
        src_x[:, None] - jnp.arange(w, dtype=jnp.float32)[None, :]))
    t = a_x @ img.T                       # (out*out, H)
    out = jnp.sum(a_y * t, axis=-1)       # (out*out,)
    return out.reshape(out_dim, out_dim)


@functools.partial(jax.jit, static_argnames=("out_dim", "out_dtype"))
def train_transform(key: jax.Array, images: jax.Array, mean: jax.Array,
                    std: jax.Array, out_dim: int,
                    out_dtype=jnp.float32) -> jax.Array:
    """uint8 (B,H,W) or (B,H,W,C) -> augmented float (B,out,out,3).

    Fused: rotate + random-resized-crop (one bilinear pass) + gray->3ch
    (ref TensorRepeat, dataloader.py:31-44) + normalize (ref :107).
    """
    b = images.shape[0]
    grayscale = images.ndim == 3
    imgs = images.astype(jnp.float32) / 255.0
    h, w = imgs.shape[1], imgs.shape[2]
    params = _sample_affine_batch(key, b, h, w)

    if grayscale:
        out = jax.vmap(_warp_one, in_axes=(0, 0, 0, 0, 0, 0, None))(
            imgs, *params, out_dim)
        out = out[..., None].repeat(3, axis=-1)
    else:
        # Same geometric params for all channels of an image.
        warp_hw = jax.vmap(
            _warp_one, in_axes=(2, None, None, None, None, None, None),
            out_axes=2)
        out = jax.vmap(warp_hw, in_axes=(0, 0, 0, 0, 0, 0, None))(
            imgs, *params, out_dim)
    return ((out - mean) / std).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dim", "out_dtype"))
def eval_transform(images: jax.Array, mean: jax.Array, std: jax.Array,
                   out_dim: int, out_dtype=jnp.float32) -> jax.Array:
    """uint8 batch -> float (B,out,out,3): resize+center-crop+normalize.

    Ref eval pipeline dataloader.py:109-116.  Inputs are square, so
    Resize(out)+CenterCrop(out) is exactly a bilinear resize to (out,out).
    """
    grayscale = images.ndim == 3
    imgs = images.astype(jnp.float32) / 255.0
    if grayscale:
        imgs = imgs[..., None]
    b, _, _, c = imgs.shape
    out = jax.image.resize(imgs, (b, out_dim, out_dim, c), method="bilinear")
    if grayscale:
        out = out.repeat(3, axis=-1)
    return ((out - mean) / std).astype(out_dtype)
