"""Batch iteration: host gather -> sharded device arrays, with prefetch.

Replaces the reference's DataLoader stack (ref dataloader.py:153-170:
NUM_WORKERS=2 worker processes, pin_memory=True, per-batch H2D copies at
ref classif.py:43-44).  TPU-native shape of the same idea:

  * the only host work per step is a numpy fancy-index gather of raw uint8
    rows (augmentation happens on device — see augment.py), so no worker
    processes are needed;
  * batches are placed as *global* jax.Arrays sharded along the batch axis
    over the 'data' mesh axis; on multi-host each process contributes the
    rows for its own chips (jax.make_array_from_process_local_data);
  * ``device_put`` is asynchronous, so a small lookahead queue (depth =
    Config.prefetch, the NUM_WORKERS analogue) double-buffers the H2D copy
    behind the previous step's compute — the pin_memory/non_blocking
    equivalent.

Each step yields (images u8, labels i32, valid bool) — ``valid`` masks the
wraparound padding the sampler added to keep shapes static (see sampler.py).
"""

from __future__ import annotations

import collections
import queue as queue_mod
import threading
import time
from typing import Iterator, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .datasets import Split
from .sampler import ShardedSampler
from .. import faults, telemetry
from ..runtime import DATA_AXIS


class ResidentLoader:
    """Device-resident mode: the whole split lives in HBM.

    For corpora that fit in device memory (MNIST's raw train split is
    42 MB), batching reduces to an on-device index gather — the host's only
    per-epoch work is computing the sampler permutation (~200 KB of int32).
    Pairs with Engine.train_epoch/eval_epoch: one XLA dispatch per epoch.

    Images/labels are replicated across the mesh; the (steps, global_batch)
    index plan is sharded over 'data' along the batch column, so device d
    gathers exactly rank d's shard — identical semantics (and identical
    sample->rank assignment) to the streaming ShardedLoader.
    """

    def __init__(self, split: Split, mesh: Mesh, batch_per_replica: int,
                 shuffle: bool, seed: int, prefetch: int = 0,
                 producer_threads: int = 0, device_prefetch: int = 0):
        # no host loop to prefetch for
        del prefetch, producer_threads, device_prefetch
        self.mesh = mesh
        self.batch_per_replica = batch_per_replica
        self.world = mesh.devices.size
        replicated = NamedSharding(mesh, P())
        self.plan_sharding = NamedSharding(mesh, P(None, DATA_AXIS))
        self.images = _put_global(split.images, replicated)
        self.labels = _put_global(split.labels, replicated)

        devs = list(mesh.devices.flat)
        local_ranks = [i for i, d in enumerate(devs)
                       if d.process_index == jax.process_index()]
        self.samplers = [
            ShardedSampler(num_samples=len(split), world_size=self.world,
                           rank=r, batch_size=batch_per_replica,
                           shuffle=shuffle, seed=seed)
            for r in local_ranks
        ]
        self.batches_per_epoch = self.samplers[0].batches_per_epoch

    def __len__(self) -> int:
        return self.batches_per_epoch

    @property
    def global_batch(self) -> int:
        return self.world * self.batch_per_replica

    def _host_plan(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        per_rank = [s.epoch_indices(epoch) for s in self.samplers]
        idx = np.concatenate([ix for ix, _ in per_rank], axis=1)
        valid = np.concatenate([v for _, v in per_rank], axis=1)
        return idx.astype(np.int32), valid

    def epoch_plan(self, epoch: int) -> Tuple[jax.Array, jax.Array]:
        """(idx, valid) device arrays of shape (steps, global_batch)."""
        idx, valid = self._host_plan(epoch)
        return (_put_global(idx, self.plan_sharding),
                _put_global(valid, self.plan_sharding))

    def epoch_plan_many(self, epochs) -> Tuple[jax.Array, jax.Array]:
        """Stacked plans (K, steps, global_batch) for multi-epoch dispatch."""
        plans = [self._host_plan(e) for e in epochs]
        sharding = NamedSharding(self.mesh, P(None, None, DATA_AXIS))
        return (_put_global(np.stack([p[0] for p in plans]), sharding),
                _put_global(np.stack([p[1] for p in plans]), sharding))


def _put_global(array: np.ndarray, sharding: NamedSharding) -> jax.Array:
    if jax.process_count() == 1:
        return jax.device_put(array, sharding)
    return jax.make_array_from_process_local_data(sharding, array)


class _ProducerFailure:
    """Wraps an exception raised on a producer thread so the consumer can
    re-raise it at the step where the batch was due."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class ShardedLoader:
    """Iterates one split as sharded global batches of shape (world*B, ...).

    ``producer_threads > 0`` moves ALL per-step host work — the numpy
    fancy-index gather and the (async) ``device_put`` dispatch — off the
    consumer thread onto background producers feeding bounded queues, so
    host production overlaps device compute instead of running serially
    between steps.  Thread t produces steps t, t+N, t+2N, ... and the
    consumer round-robins the queues, so the batch stream is byte-identical
    (values AND order) to the synchronous path.  0 = the synchronous
    reference behavior (and what direct library constructions default to;
    the CLI default is 1 — see Config.producer_threads).

    ``device_prefetch > 0`` adds a device-side double-buffer stage on top:
    a dedicated transfer thread issues the sharded ``jax.device_put`` for
    batches t+1..t+N into a bounded device queue while the consumer
    computes step t, so the H2D copy overlaps device work instead of
    serializing inside the step loop.  It composes with
    ``producer_threads`` (producers then gather HOST arrays only; the
    single transfer thread owns ALL device placement, in step order, so
    the stream stays byte-identical) and with elastic
    ``release()``/``reshard()`` (in-flight transfers are stopped, drained
    and joined).  Consumer blocking on the device queue is charged to the
    ``data/device_wait_s`` telemetry counter; the goodput ledger's
    ``data_wait`` still sees it through the step loop's inter-step window
    (cli._run_train_pass) — see ``epoch()``.
    """

    def __init__(self, split: Split, mesh: Mesh, batch_per_replica: int,
                 shuffle: bool, seed: int, prefetch: int = 2,
                 producer_threads: int = 0, device_prefetch: int = 0):
        self.split = split
        self.mesh = mesh
        self.batch_per_replica = batch_per_replica
        self.shuffle = shuffle
        self.seed = seed
        # prefetch=0: strictly synchronous put->step alternation.  On the
        # virtual-CPU test mesh an H2D transfer still in flight while an
        # 8-participant all-reduce executes can deadlock XLA:CPU's
        # collective rendezvous (single physical core); real TPUs overlap
        # these fine, so 0 is only for that environment.
        self.prefetch = max(0, prefetch)
        self.producer_threads = max(0, producer_threads)
        self.device_prefetch = max(0, device_prefetch)
        self.world = mesh.devices.size
        self.sharding = NamedSharding(mesh, P(DATA_AXIS))

        # This process's slice of the global rank space.  Mesh device order
        # is the global batch order; rows for device d sit at block d.
        devs = list(mesh.devices.flat)
        self.local_ranks = [i for i, d in enumerate(devs)
                            if d.process_index == jax.process_index()]
        self.samplers = [
            ShardedSampler(num_samples=len(split), world_size=self.world,
                           rank=r, batch_size=batch_per_replica,
                           shuffle=shuffle, seed=seed)
            for r in self.local_ranks
        ]
        self.batches_per_epoch = self.samplers[0].batches_per_epoch
        # Prefetch-queue observability state (ADVICE #4), keyed PER
        # EPOCH GENERATOR: ``_queues[epoch]`` is that epoch() call's
        # live lookahead structure (synchronous path: a deque of device
        # batches; threaded path: the list of bounded per-producer
        # queues), so two interleaved iterations no longer clobber each
        # other's view (tests/test_pipeline.py interleaved regression).
        # ``_queue`` stays as the most-recently-started epoch's
        # structure for existing consumers; ``queue_for(epoch)`` is the
        # keyed accessor.  Entries persist after exhaustion (tests read
        # them post-epoch), bounded to the newest few.
        self._queues: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()
        # Live background machinery (threaded/device-prefetch epochs):
        # each entry holds the stop event, threads and bounded queues of
        # one in-flight epoch generator, so ``release()`` can stop,
        # drain, and join them even while transfers are in flight.
        self._active_runs: list = []
        self._runs_lock = threading.Lock()

    _QUEUE_HISTORY = 8  # retained per-epoch entries (newest kept)

    def _register_queue(self, epoch: int, queue) -> None:
        self._queues.pop(epoch, None)
        self._queues[epoch] = queue
        while len(self._queues) > self._QUEUE_HISTORY:
            self._queues.popitem(last=False)

    @property
    def _queue(self):
        """Most-recently-started epoch's lookahead structure (None
        before the first prefetching iteration)."""
        return next(reversed(self._queues.values())) \
            if self._queues else None

    def queue_for(self, epoch: int):
        """The lookahead structure of a specific epoch() generator."""
        return self._queues.get(epoch)

    def lookahead_depth(self, epoch: int):
        """Total buffered lookahead of an epoch's queue structure (None
        before its first prefetching iteration) — the flight recorder's
        per-step queue-depth sample (flightrec.py)."""
        q = self._queues.get(epoch)
        if q is None:
            return None
        if isinstance(q, list):  # threaded path: per-producer queues
            return sum(x.qsize() for x in q)
        return len(q)  # synchronous path: one deque

    def _register_run(self, run: dict) -> None:
        with self._runs_lock:
            self._active_runs.append(run)

    def _unregister_run(self, run: dict) -> None:
        with self._runs_lock:
            try:
                self._active_runs.remove(run)
            except ValueError:
                pass

    @staticmethod
    def _drain(q) -> None:
        while True:
            try:
                q.get_nowait()
            except queue_mod.Empty:
                break

    @classmethod
    def _shutdown_run(cls, run: dict) -> None:
        """Stop one epoch's background machinery: signal, unblock any
        producer parked on a full queue, join, then drop whatever device
        batches the join race let through."""
        run["stop"].set()
        for q in run["queues"]:
            cls._drain(q)
        for th in run["threads"]:
            th.join()
        for q in run["queues"]:
            cls._drain(q)

    def release(self) -> None:
        """Drop every device-backed reference — mesh, sharding, prefetch
        queues (their entries are device batches) — keeping only the
        plain-host fields ``reshard`` needs.  Elastic pre-teardown
        (cli.run_train): the old world's backend cannot be destroyed,
        and its gloo sockets closed, while loader state pins it.
        Background epochs (threaded producers, device-prefetch transfer
        threads) are stopped, drained and JOINED first, so no in-flight
        ``device_put`` outlives the mesh it targets."""
        with self._runs_lock:
            runs = list(self._active_runs)
        for run in runs:
            self._shutdown_run(run)
        with self._runs_lock:
            self._active_runs.clear()
        self.mesh = None
        self.sharding = None
        self._queues.clear()

    def reshard(self, mesh: Mesh) -> "ShardedLoader":
        """A fresh loader over the SAME split/settings on a NEW mesh —
        the elastic reconfigure path (cli.py): after a world shrink the
        rank space changes size, so every sampler must be re-derived.
        Because shard assignment is a pure function of
        (num_samples, world, rank, seed, epoch) — one global epoch-keyed
        permutation, rank slice ``perm[rank::world]`` — the re-derived
        loader enumerates exactly the full dataset for the new world,
        identically to a loader BORN at that world size (property-tested
        in tests/test_elastic.py).  No state carries over: epoch
        generators and prefetch queues belong to the old world.
        """
        return ShardedLoader(self.split, mesh, self.batch_per_replica,
                             shuffle=self.shuffle, seed=self.seed,
                             prefetch=self.prefetch,
                             producer_threads=self.producer_threads,
                             device_prefetch=self.device_prefetch)

    def __len__(self) -> int:
        return self.batches_per_epoch

    @property
    def global_batch(self) -> int:
        return self.world * self.batch_per_replica

    def _host_batch(self, per_rank, step: int):
        """One step's host gather (the only per-step host compute) — a
        method so tests can inject slowness/failures into either the
        synchronous or the threaded production path."""
        idx = np.concatenate([ix[step] for ix, _ in per_rank])
        valid = np.concatenate([v[step] for _, v in per_rank])
        return self.split.images[idx], self.split.labels[idx], valid

    def _host_batch_fn(self):
        """``self._host_batch``, or its fault-injecting/retrying twin
        when the installed fault plan targets ``data.host_batch`` —
        resolved ONCE per epoch, so without a plan the per-step hot
        path carries no fault plumbing at all (acceptance criterion:
        zero-cost when disabled)."""
        if not faults.targets("data.host_batch"):
            return self._host_batch

        def faulty(per_rank, step):
            def attempt():
                faults.fire("data.host_batch")
                return self._host_batch(per_rank, step)

            return faults.retry(attempt, "data.host_batch")

        return faulty

    def _host_batches(self, epoch: int):
        per_rank = [s.epoch_indices(epoch) for s in self.samplers]
        host_batch = self._host_batch_fn()
        for step in range(self.batches_per_epoch):
            yield host_batch(per_rank, step)

    def _to_device(self, arrays) -> Tuple[jax.Array, ...]:
        if jax.process_count() == 1:
            return tuple(jax.device_put(a, self.sharding) for a in arrays)
        return tuple(
            jax.make_array_from_process_local_data(self.sharding, a)
            for a in arrays)

    def epoch(self, epoch: int) -> Iterator[Tuple[jax.Array, jax.Array,
                                                  jax.Array]]:
        """Async-prefetched iterator over one epoch's sharded batches.

        ``producer_threads > 0`` dispatches to the threaded path
        (``_threaded_epoch``): production fully overlaps consumption and
        ``data/wait_s`` measures true consumer blocking.  Otherwise, with
        telemetry enabled (telemetry.py) the instrumented twin of each
        synchronous loop runs instead, feeding the counters:
        ``data/wait_s`` (steady-state host time producing+enqueueing
        batches between yields — the data-wait half of the
        data-vs-compute split; device_put is async so this is pure host
        work), ``data/warmup_s`` (the prefetch initial fill, which runs
        before the consumer requested anything), ``data/batches``,
        ``data/starved_steps`` (consumer found no lookahead in the
        queue: H2D could not overlap that step), and
        ``data/queue_depth_sum`` (divide by batches for mean depth).
        Every per-step wait is also observed into the ``data/wait_s``
        HISTOGRAM, so the report prints p50/p95/p99 wait latencies next
        to the totals.  The disabled path is the original loop,
        untouched — no clock reads, no counter lookups per step.

        The goodput ledger (goodput.py) deliberately does NOT hook this
        iterator: its ``data_wait`` category is charged once, from the
        train loop's own inter-step wait window (cli._run_train_pass),
        which already contains any blocking that happens here.  Charging
        both would double-count and break the sums-to-wall invariant.
        """
        tel = telemetry.get()
        if self.device_prefetch > 0:
            yield from self._device_prefetch_epoch(epoch, tel)
            return
        if self.producer_threads > 0:
            yield from self._threaded_epoch(epoch, tel)
            return
        host_iter = self._host_batches(epoch)
        if self.prefetch == 0:
            if not tel.enabled:
                for arrays in host_iter:
                    yield self._to_device(arrays)
                return
            wait = tel.counter("data/wait_s")
            wait_hist = tel.histogram("data/wait_s")
            batches = tel.counter("data/batches")
            while True:
                t0 = time.perf_counter()
                try:
                    arrays = self._to_device(next(host_iter))
                except StopIteration:
                    return
                dt = time.perf_counter() - t0
                wait.add(dt)
                wait_hist.observe(dt)
                batches.add(1)
                yield arrays
        # Registered (not just a local) so tests/bench can assert the
        # overlap actually happens: in steady state the queue holds the
        # next batch(es) — already device_put, H2D in flight — while the
        # consumer computes on the previous one.
        queue = collections.deque()
        self._register_queue(epoch, queue)
        if not tel.enabled:
            try:
                while len(queue) < self.prefetch:
                    queue.append(self._to_device(next(host_iter)))
            except StopIteration:
                pass
            while queue:
                yield queue.popleft()
                try:
                    queue.append(self._to_device(next(host_iter)))
                except StopIteration:
                    pass
            return
        wait = tel.counter("data/wait_s")
        wait_hist = tel.histogram("data/wait_s")
        batches = tel.counter("data/batches")
        starved = tel.counter("data/starved_steps")
        depth_sum = tel.counter("data/queue_depth_sum")
        exhausted = False
        t0 = time.perf_counter()
        try:
            while len(queue) < self.prefetch:
                queue.append(self._to_device(next(host_iter)))
        except StopIteration:
            exhausted = True
        # The initial fill runs before the consumer has requested a single
        # batch — it is producer work, not consumer blocking, so it goes
        # to its own counter and wait_s means steady-state blocking only.
        tel.counter("data/warmup_s").add(time.perf_counter() - t0)
        while queue:
            depth_sum.add(len(queue))
            if len(queue) == 1 and not exhausted:
                # handing out the last buffered batch with more data
                # still to come: the next step's H2D has nothing in
                # flight to hide behind
                starved.add(1)
            batches.add(1)
            yield queue.popleft()
            t0 = time.perf_counter()
            try:
                queue.append(self._to_device(next(host_iter)))
            except StopIteration:
                exhausted = True
            dt = time.perf_counter() - t0
            wait.add(dt)
            wait_hist.observe(dt)

    def _device_prefetch_epoch(self, epoch: int, tel):
        """Device-side double-buffered iterator: ONE transfer thread
        issues the sharded ``device_put`` for upcoming batches into a
        bounded device queue (maxsize = ``device_prefetch``) while the
        consumer computes the current step — H2D overlaps compute even
        when the consumer thread never yields the GIL between steps.

        Composition with ``producer_threads > 0``: producer threads do
        the numpy gather only (HOST arrays into their bounded per-thread
        queues, thread t owning steps t, t+N, ...); the transfer thread
        round-robins them in step order and owns every device placement,
        so the stream stays byte-identical (values AND order) to the
        synchronous path — same contract as ``_threaded_epoch``.

        Shutdown: the generator's ``finally`` — or an elastic
        ``release()`` racing it — sets the stop event, drains every
        queue (dropping in-flight device batches), and joins all
        threads; no transfer outlives its epoch or its mesh.

        Telemetry (enabled path): consumer blocking on the device queue
        is charged to ``data/device_wait_s`` (counter + histogram) — its
        own counter, NOT ``data/wait_s``, so reports can split "host
        production stalled" from "H2D did not overlap".  The goodput
        ledger's ``data_wait`` category still captures this blocking via
        the step loop's inter-step window (cli._run_train_pass); this
        iterator deliberately charges goodput nothing (see ``epoch()``).
        """
        nb = self.batches_per_epoch
        stop = threading.Event()
        dev_q = queue_mod.Queue(maxsize=self.device_prefetch)
        per_rank = [s.epoch_indices(epoch) for s in self.samplers]
        host_batch = self._host_batch_fn()
        host_queues: list = []
        threads: list = []

        def _put(q, item) -> None:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return
                except queue_mod.Full:
                    continue

        if self.producer_threads > 0:
            nthreads = self.producer_threads
            host_queues = [queue_mod.Queue(maxsize=max(1, self.prefetch))
                           for _ in range(nthreads)]

            def produce(t: int, q) -> None:
                try:
                    for step in range(t, nb, nthreads):
                        if stop.is_set():
                            return
                        _put(q, host_batch(per_rank, step))
                except BaseException as e:  # propagate via the stream
                    _put(q, _ProducerFailure(e))

            threads = [
                threading.Thread(
                    target=produce, args=(t, host_queues[t]),
                    name=f"dpt-gather-{epoch}-{t}", daemon=True)
                for t in range(nthreads)
            ]

            def host_stream():
                for step in range(nb):
                    q = host_queues[step % nthreads]
                    while not stop.is_set():
                        try:
                            yield q.get(timeout=0.05)
                            break
                        except queue_mod.Empty:
                            continue
                    else:
                        return
        else:
            def host_stream():
                for step in range(nb):
                    if stop.is_set():
                        return
                    yield host_batch(per_rank, step)

        def transfer() -> None:
            try:
                for item in host_stream():
                    if isinstance(item, _ProducerFailure):
                        _put(dev_q, item)
                        return
                    _put(dev_q, self._to_device(item))
            except BaseException as e:
                # transfer thread: ANY failure (device_put OOM included)
                # must reach the consumer as a _ProducerFailure or the
                # step loop blocks on dev_q forever
                _put(dev_q, _ProducerFailure(e))

        threads.append(threading.Thread(
            target=transfer, name=f"dpt-h2d-{epoch}", daemon=True))
        all_queues = [dev_q] + host_queues
        self._register_queue(epoch, all_queues)
        run = {"stop": stop, "threads": threads, "queues": all_queues}
        self._register_run(run)
        for th in threads:
            th.start()
        enabled = tel.enabled
        if enabled:
            dwait = tel.counter("data/device_wait_s")
            dwait_hist = tel.histogram("data/device_wait_s")
            batches = tel.counter("data/batches")
            starved = tel.counter("data/starved_steps")
            depth_sum = tel.counter("data/queue_depth_sum")
        try:
            for _step in range(nb):
                if enabled:
                    depth_sum.add(sum(q.qsize() for q in all_queues))
                    if dev_q.empty():
                        starved.add(1)
                    t0 = time.perf_counter()
                    item = dev_q.get()
                    dt = time.perf_counter() - t0
                    dwait.add(dt)
                    dwait_hist.observe(dt)
                    batches.add(1)
                else:
                    item = dev_q.get()
                if isinstance(item, _ProducerFailure):
                    raise item.exc
                yield item
        finally:
            self._shutdown_run(run)
            self._unregister_run(run)

    def _threaded_epoch(self, epoch: int, tel):
        """Background-producer iterator: host gather + device_put dispatch
        run on ``producer_threads`` threads feeding bounded queues.

        Ordering: thread t owns steps t, t+N, ... and its own queue; the
        consumer round-robins queues in step order, so the stream is
        byte-identical to the synchronous path for any N.  Shutdown: the
        generator's ``finally`` (normal exhaustion, ``close()``, or a
        consumer exception) sets the stop event, drains the queues, and
        joins every producer — no thread outlives its epoch.  A producer
        exception travels through its queue and re-raises on the consumer
        at the step whose batch it replaced.

        Telemetry (enabled path only): ``data/wait_s`` is TRUE consumer
        blocking — time spent in ``queue.get`` — not producer work;
        ``data/starved_steps`` counts get() calls that found the next
        queue empty; ``data/queue_depth_sum`` samples the total buffered
        lookahead across queues once per batch.
        """
        nthreads = self.producer_threads
        depth = max(1, self.prefetch)
        per_rank = [s.epoch_indices(epoch) for s in self.samplers]
        stop = threading.Event()
        queues = [queue_mod.Queue(maxsize=depth) for _ in range(nthreads)]
        # Tests/bench introspection parity with the sync path: expose the
        # bounded queues as this epoch's lookahead structure.
        self._register_queue(epoch, queues)

        def _put(q, item) -> None:
            # Bounded put that aborts promptly once the consumer is gone.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return
                except queue_mod.Full:
                    continue

        host_batch = self._host_batch_fn()

        def produce(t: int, q) -> None:
            try:
                for step in range(t, self.batches_per_epoch, nthreads):
                    if stop.is_set():
                        return
                    _put(q, self._to_device(host_batch(per_rank, step)))
            except BaseException as e:  # propagate to the consumer
                _put(q, _ProducerFailure(e))

        threads = [
            threading.Thread(target=produce, args=(t, queues[t]),
                             name=f"dpt-producer-{epoch}-{t}", daemon=True)
            for t in range(nthreads)
        ]
        run = {"stop": stop, "threads": threads, "queues": queues}
        self._register_run(run)
        for th in threads:
            th.start()
        enabled = tel.enabled
        if enabled:
            wait = tel.counter("data/wait_s")
            wait_hist = tel.histogram("data/wait_s")
            batches = tel.counter("data/batches")
            starved = tel.counter("data/starved_steps")
            depth_sum = tel.counter("data/queue_depth_sum")
        try:
            for step in range(self.batches_per_epoch):
                q = queues[step % nthreads]
                if enabled:
                    depth_sum.add(sum(x.qsize() for x in queues))
                    if q.empty():
                        starved.add(1)
                    t0 = time.perf_counter()
                    item = q.get()
                    dt = time.perf_counter() - t0
                    wait.add(dt)
                    wait_hist.observe(dt)
                    batches.add(1)
                else:
                    item = q.get()
                if isinstance(item, _ProducerFailure):
                    raise item.exc
                yield item
        finally:
            self._shutdown_run(run)
            self._unregister_run(run)
