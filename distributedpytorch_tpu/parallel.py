"""Model-axis parallelism over the 2-D (data, model) mesh — TWO distinct
strategies behind the same 'model' axis:

1. **ZeRO-3/FSDP-style parameter/optimizer sharding** (``state_sharding``,
   what ``--model-parallel N`` alone enables): large parameter tensors —
   and, because the rule is purely shape-driven, their optimizer moments —
   are sharded across MODEL_AXIS.  Under jit, XLA (GSPMD) inserts the
   all-gathers needed AROUND each matmul, so the step's *math* and its
   *compute distribution* are unchanged; only the storage layout is.  This
   buys per-chip parameter/optimizer memory (divided by the model-axis
   size) at the cost of gather traffic on ICI — it is NOT compute
   parallelism: every device still runs every matmul at full size, on
   gathered weights, with fully-replicated activations.

2. **Tensor parallelism** (``make_tp_constrain``, what ``--tensor-parallel``
   adds for the vit family): Megatron-style sharded COMPUTE.  Activation
   sharding constraints pin the attention-head and MLP-hidden axes to
   MODEL_AXIS; GSPMD then partitions the matmuls themselves — each device
   computes only its head/hidden slice (column-parallel up-projection,
   row-parallel down-projection) and XLA inserts the one all-reduce per
   block that Megatron-TP requires.  Per-device ACTIVATION memory and
   per-device FLOPs both drop by the model-axis size; weights stay laid
   out however (1) placed them — the two strategies compose.

The reference has neither (SURVEY §2 parallelism checklist: TP ABSENT,
ZeRO ABSENT; data parallelism is its only strategy) — both are TPU-native
framework additions on the axis ``runtime.make_mesh`` reserves.

Numerical equivalence with the replicated layout is proven in
tests/test_parallel.py (ZeRO) and tests/test_tensor_parallel.py (TP:
logits equal with identical params; e2e training equal; per-device
activation memory measured smaller).

Usage:
    mesh = runtime.make_mesh(model_parallel=2)      # (data=4, model=2)
    state = jax.device_put(state, parallel.state_sharding(state, mesh))
    state, metrics = engine.train_step(state, images, labels, valid, key)
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .runtime import MODEL_AXIS

# Tensors smaller than this stay replicated: sharding a 64-element bias
# saves nothing and costs a gather.  2^14 f32 = 64 KiB.
MIN_SHARD_ELEMENTS = 2 ** 14


def leaf_spec(shape, model_parallel: int,
              min_elements: int = MIN_SHARD_ELEMENTS,
              prefer_axis0: bool = False) -> P:
    """PartitionSpec for one tensor: largest mp-divisible axis -> MODEL_AXIS.

    ``prefer_axis0`` picks axis 0 when divisible (the pipeline-parallel
    layout: stacked per-stage block parameters live on their stage's
    devices, so the pipeline's shard_map finds them already in place).

    Replicates when the mesh has no model axis to use, the tensor is small,
    or no axis is divisible — sharding must never change which tensors are
    representable, only where they live.
    """
    if model_parallel <= 1 or int(np.prod(shape)) < min_elements:
        return P()
    divisible = [i for i in range(len(shape))
                 if shape[i] % model_parallel == 0]
    if not divisible:
        return P()
    if prefer_axis0 and 0 in divisible:
        axis = 0
    else:
        axis = max(divisible, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = MODEL_AXIS
    return P(*spec)


def tree_sharding(tree: Any, mesh: Mesh,
                  min_elements: int = MIN_SHARD_ELEMENTS,
                  prefer_axis0: bool = False) -> Any:
    """NamedSharding pytree for any param-shaped tree (params, grads,
    optimizer moments — the rule is shape-only, so moments land on the same
    layout as the params they track)."""
    mp = mesh.shape[MODEL_AXIS]

    def one(leaf):
        return NamedSharding(mesh, leaf_spec(np.shape(leaf), mp,
                                             min_elements, prefer_axis0))

    return jax.tree_util.tree_map(one, tree)


def state_sharding(state: Any, mesh: Mesh,
                   min_elements: int = MIN_SHARD_ELEMENTS,
                   prefer_axis0: bool = False) -> Any:
    """Sharding tree for a whole TrainState (params + batch_stats +
    opt_state + step).  Scalars and batch stats fall below the size floor
    and stay replicated automatically."""
    return tree_sharding(state, mesh, min_elements, prefer_axis0)


def make_tp_constrain(mesh: Mesh):
    """Activation-sharding hook for tensor parallelism (strategy 2 above).

    Returns ``constrain(x, spec)`` applying
    ``jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))``
    — models thread it through their forward pass (models/vit.py
    ``tp_constrain``) to pin head/hidden axes to MODEL_AXIS and the batch
    axis to the data axis.  A constraint whose sharded dimension is not
    divisible by its mesh-axis size is skipped (shape check is static at
    trace time): that keeps tiny init-time dummy batches and odd eval
    tails valid — GSPMD simply propagates its own choice there.
    """

    def constrain(x: jax.Array, spec) -> jax.Array:
        for dim, axis in zip(x.shape, spec):
            if axis is not None and dim % mesh.shape[axis]:
                return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))

    return constrain
