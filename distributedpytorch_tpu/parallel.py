"""Parameter/optimizer sharding over the 2-D (data, model) mesh.

The reference's only strategy is data parallelism (SURVEY §2 parallelism
checklist): params replicated, gradients all-reduced.  This module adds the
TPU-native extension on top of the same mesh (runtime.make_mesh's 'model'
axis): shard large parameter tensors — and, because the rule is purely
shape-driven, their optimizer moments — across MODEL_AXIS.  Under jit, XLA
(GSPMD) inserts the all-gathers/reduce-scatters needed around each matmul,
so the train step's *math* is unchanged; only the layout is.  That is the
compiler-native equivalent of ZeRO-3/FSDP: per-chip memory for sharded
tensors drops by the model-axis size, at the cost of gather traffic on ICI.

Numerical equivalence with the replicated layout is proven in
tests/test_parallel.py (same step, same batch, 1-D mesh vs 2-D
data×model mesh, params bitwise-comparable to tolerance).

Usage:
    mesh = runtime.make_mesh(model_parallel=2)      # (data=4, model=2)
    state = jax.device_put(state, parallel.state_sharding(state, mesh))
    state, metrics = engine.train_step(state, images, labels, valid, key)
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .runtime import MODEL_AXIS

# Tensors smaller than this stay replicated: sharding a 64-element bias
# saves nothing and costs a gather.  2^14 f32 = 64 KiB.
MIN_SHARD_ELEMENTS = 2 ** 14


def leaf_spec(shape, model_parallel: int,
              min_elements: int = MIN_SHARD_ELEMENTS) -> P:
    """PartitionSpec for one tensor: largest mp-divisible axis -> MODEL_AXIS.

    Replicates when the mesh has no model axis to use, the tensor is small,
    or no axis is divisible — sharding must never change which tensors are
    representable, only where they live.
    """
    if model_parallel <= 1 or int(np.prod(shape)) < min_elements:
        return P()
    divisible = [i for i in range(len(shape))
                 if shape[i] % model_parallel == 0]
    if not divisible:
        return P()
    axis = max(divisible, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = MODEL_AXIS
    return P(*spec)


def tree_sharding(tree: Any, mesh: Mesh,
                  min_elements: int = MIN_SHARD_ELEMENTS) -> Any:
    """NamedSharding pytree for any param-shaped tree (params, grads,
    optimizer moments — the rule is shape-only, so moments land on the same
    layout as the params they track)."""
    mp = mesh.shape[MODEL_AXIS]

    def one(leaf):
        return NamedSharding(mesh, leaf_spec(np.shape(leaf), mp,
                                             min_elements))

    return jax.tree_util.tree_map(one, tree)


def state_sharding(state: Any, mesh: Mesh,
                   min_elements: int = MIN_SHARD_ELEMENTS) -> Any:
    """Sharding tree for a whole TrainState (params + batch_stats +
    opt_state + step).  Scalars and batch stats fall below the size floor
    and stay replicated automatically."""
    return tree_sharding(state, mesh, min_elements)
