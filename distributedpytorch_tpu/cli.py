"""L4/L6: drivers + launcher (ref classif.py:75-243 and main.py:112-142).

``run_train``/``run_test`` replicate the reference drivers' orchestration
and log formats; ``main`` is the CLI entry.  There is no process spawn: JAX
is SPMD within a process (one process drives all local chips), and on pods
each host runs this same command — the runtime handles rendezvous
(vs. ref main.py:128-135's env vars + torch.multiprocessing.spawn).
"""

from __future__ import annotations

import logging
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import checkpoint as ckpt
from . import costs, elastic, faults, flightrec, goodput, parallel, \
    runtime, telemetry, tracing, utils
from .config import Config, config_from_argv
from .data import augment  # noqa: F401  (re-exported for drivers/tests)
from .data.datasets import Dataset, Split, load_dataset
from .data.pipeline import ResidentLoader, ShardedLoader
from .models import get_model, get_model_input_size, pretrained
from .ops.losses import get_loss_fn
from .train.engine import Engine, make_optimizer


def _build_engine(cfg: Config, model_name: str, dataset: Dataset,
                  steps_per_epoch: int, mesh=None) -> Engine:
    policy = cfg.precision_policy()
    model = get_model(model_name, dataset.nb_classes,
                      half_precision=cfg.half_precision,
                      attention=cfg.attention, mesh=mesh,
                      tensor_parallel=cfg.tensor_parallel,
                      pipeline_parallel=cfg.pipeline_parallel,
                      pipeline_microbatches=cfg.pipeline_microbatches,
                      moe_experts=cfg.moe_experts,
                      precision=policy, remat=cfg.remat,
                      scan_layers=cfg.scan_layers)
    # Working weighted/focal losses (fixes SURVEY defect #4).
    class_weights = (dataset.class_weights()
                     if cfg.loss in ("weighted_cross_entropy", "focal_loss")
                     else None)
    loss_fn = get_loss_fn(cfg.loss, class_weights, cfg.focal_gamma)
    tx = make_optimizer(cfg.optimizer, cfg.learning_rate, cfg.momentum,
                        cfg.lr_step_gamma, steps_per_epoch,
                        cfg.feature_extract)
    return Engine(model, model_name, loss_fn, tx, dataset.mean, dataset.std,
                  get_model_input_size(model_name),
                  grad_accum=cfg.grad_accum,
                  precision=policy, remat=cfg.remat)


def _place_state(state, mesh, cfg: Config):
    """Replicated (reference semantics) or model-axis-sharded placement
    (--model-parallel > 1; see parallel.py).  Pipeline runs prefer the
    stacked (depth,) axis so each stage's block weights live on its own
    devices."""
    if cfg.model_parallel > 1:
        return jax.device_put(
            state, parallel.state_sharding(
                state, mesh, prefer_axis0=cfg.pipeline_parallel))
    return jax.device_put(state, runtime.replicated_sharding(mesh))


RESIDENT_HBM_FRACTION = 0.3


def _resident_budget_bytes(cfg: Config) -> int:
    """Byte cap for keeping one split device-resident under 'auto'.

    Residency replicates the raw split to EVERY device (pipeline.py
    ResidentLoader), so the cost is per-replica HBM.  The budget is the
    configured --resident-max-bytes cap, further bounded to 30% of the
    device's reported memory when the backend exposes it — train and valid
    splits are both resident (~1.1x train combined), and params, optimizer
    state, activations, and XLA workspace need the rest, so 30% per split
    keeps a documented >=40% headroom even in the worst case.  Explicit
    --data-mode resident bypasses this (the user asserted it fits).
    """
    budget = cfg.resident_max_bytes
    hbm = runtime.device_memory_limit()
    if hbm is not None:
        budget = min(budget, int(RESIDENT_HBM_FRACTION * hbm))
    return budget


def _validate_precision(cfg: Config) -> None:
    """Precision/remat knob validation, before any dataset/model cost.

    Covers programmatic Config construction too (argparse already
    restricts the CLI choices)."""
    if cfg.precision is not None and cfg.precision not in (
            "f32", "bf16", "bf16_full", "f16"):
        raise ValueError(
            f"--precision must be f32|bf16|bf16_full|f16, "
            f"got {cfg.precision!r}")
    if cfg.remat not in ("none", "blocks", "full"):
        raise ValueError(
            f"--remat must be none|blocks|full, got {cfg.remat!r}")
    if cfg.precision not in (None, "f32") and not cfg.half_precision:
        raise ValueError(
            f"--no-bf16 conflicts with --precision {cfg.precision}: "
            "--no-bf16 is the legacy alias for --precision f32; drop one")
    if cfg.precision == "f16" and jax.default_backend() == "tpu":
        raise ValueError(
            "--precision f16 is for non-TPU backends only: the MXU has "
            "no native f16 path (bf16 needs no loss scaling on TPU — "
            "use --precision bf16)")


def _validate_ckpt_format(cfg: Config) -> None:
    """Fail typos and a missing orbax up front (CLI argparse already
    restricts choices; this covers programmatic Config construction and
    surfaces the orbax dependency before any training happens)."""
    if cfg.ckpt_format not in ("msgpack", "orbax"):
        raise ValueError(
            f"ckpt_format must be 'msgpack' or 'orbax', "
            f"got {cfg.ckpt_format!r}")
    if cfg.ckpt_format == "orbax":
        ckpt.require_orbax()


def _saveable_state(cfg: Config, state):
    """What the checkpoint writer receives: msgpack needs the collective
    all-gather (every process participates); orbax saves sharded state
    as-laid-out, so no gather at all."""
    if cfg.ckpt_format == "orbax":
        return state
    return ckpt.gather_replicated(state)


def _save_ckpt(cfg: Config, path: str, model_name: str, saveable,
               epoch: int, best_valid_loss: float, saver=None) -> None:
    """msgpack: main-only file write; orbax: EVERY process calls (each
    host writes its own shards).  With --ckpt-async (``saver`` set) only
    the snapshot blocks; the write is queued on the background writer."""
    if cfg.ckpt_format == "orbax":
        if saver is not None:
            ckpt.save_checkpoint_async(saver, path, model_name, saveable,
                                       epoch, best_valid_loss, fmt="orbax")
        else:
            ckpt.save_checkpoint(path, model_name, saveable, epoch,
                                 best_valid_loss, fmt="orbax")
    elif runtime.is_main():
        if saver is not None:
            # graftlint: disable=collective-divergence -- default fmt is msgpack: a main-only single-file write; the statically-reachable orbax barrier branch inside save_checkpoint* is infeasible here (fmt never set to "orbax" on this path)
            ckpt.save_checkpoint_async(saver, path, model_name, saveable,
                                       epoch, best_valid_loss)
        else:
            # graftlint: disable=collective-divergence -- default fmt is msgpack: main-only write, no barrier on this path (see pragma above)
            ckpt.save_checkpoint(path, model_name, saveable, epoch,
                                 best_valid_loss)


def _rotate_ckpt(cfg: Config, saver, model_name: str, epoch: int) -> None:
    """Rolling-file rotation, ordered with the async writer: a pending
    background write of epoch-1's file must land BEFORE the delete, or
    the write would resurrect the file after rotation and leak it."""
    if not runtime.is_main():
        return
    if saver is not None:
        saver.submit(lambda: ckpt.rotate_checkpoint(
            cfg.rsl_path, cfg.dataset, model_name, epoch,
            keep=cfg.keep_ckpts))
    else:
        ckpt.rotate_checkpoint(cfg.rsl_path, cfg.dataset, model_name,
                               epoch, keep=cfg.keep_ckpts)


def _make_loader(cfg: Config, split: Split, mesh, shuffle: bool):
    """Pick resident (whole split in HBM, one dispatch per epoch) vs
    streamed batching.  'auto' keeps small corpora on device, bounded by
    the actual device memory (see _resident_budget_bytes)."""
    resident = (cfg.data_mode == "resident"
                or (cfg.data_mode == "auto"
                    and split.images.nbytes <= _resident_budget_bytes(cfg)))
    cls = ResidentLoader if resident else ShardedLoader
    return cls(split, mesh, cfg.batch_size, shuffle=shuffle, seed=cfg.seed,
               prefetch=cfg.prefetch,
               producer_threads=cfg.producer_threads,
               device_prefetch=cfg.device_prefetch)


def _mfu_factors(engine: Engine) -> tuple:
    """(flops_per_sample, peak_flops_per_chip, peak_dtype) for the
    telemetry MFU gauge — analytic model FLOPs (engine.init_state's jaxpr
    count) over the chip's peak AT THE RUN'S COMPUTE DTYPE (ops.flops
    per-dtype table): a bf16 run divides by the bf16 peak, an f32 run by
    the f32 peak, so MFU is never inflated by mismatched denominators.
    flops/peak may be None (untraceable model / unknown device kind,
    e.g. CPU); the gauge is then omitted."""
    from .ops.flops import dtype_label, peak_flops

    fps = getattr(engine, "_flops_per_sample", None)
    label = dtype_label(engine.compute_dtype)
    devs = jax.devices()
    peak = peak_flops(devs[0].device_kind, label) if devs else None
    return fps, peak, label


def _record_throughput(tel, sps_chip: float, fps, peak, epoch: int,
                       peak_dtype: str = "bf16") -> None:
    """North-star gauges, per epoch: samples/s/chip always; MFU as a
    fraction of the chip's per-dtype peak when the model FLOPs and the
    peak are both known, an explicit recorded null otherwise (CPU /
    unknown device kind) so every run's JSONL documents the metric.  The
    denominator's dtype is recorded beside the value — an MFU number
    without its peak dtype is unverifiable."""
    tel.gauge("throughput/samples_per_sec_per_chip").set(sps_chip,
                                                         epoch=epoch)
    if fps and peak:
        tel.gauge("throughput/mfu").set(sps_chip * fps / peak, epoch=epoch,
                                        peak_dtype=peak_dtype)
    else:
        tel.gauge("throughput/mfu").set(
            None, epoch=epoch, peak_dtype=peak_dtype,
            reason="unknown_peak" if fps else "unknown_model_flops")


def _sds(shape, dtype, sharding) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)


def _aot_warmup(cfg: Config, engine: Engine, state, train_loader,
                valid_loader, root, start_epoch: int) -> None:
    """--aot-warmup: lower+compile the epoch-0 train/eval programs against
    abstract batch shapes BEFORE the first epoch, so step-1 latency is
    bounded by a measured, recorded compile instead of surprising the
    first dispatch.  With the persistent compilation cache enabled, a
    second run of the same config turns this into a disk hit — recorded
    as ``compile/cache_hit = 1`` with a much smaller ``compile/warmup_s``.

    The compiled executables are NOT kept: the warmup's value is filling
    the persistent cache (and XLA's backend caches) so the training
    loop's own jit dispatch compiles from cache, not from scratch.
    Before each one is dropped, its ``cost_analysis()`` FLOPs/bytes are
    recorded into the shared cost registry (costs.py) and saved to
    ``RSL_PATH/costs.json`` — MFU math and profile_breakdown read the
    same provenance-stamped numbers the warmup measured.
    """
    tel = telemetry.get()
    hits_before = runtime.compilation_cache_hits()
    t0 = time.perf_counter()
    key = utils.fold_key(root, start_epoch)

    def plan(loader, stacked=0):
        steps = (loader.batches_per_epoch, loader.global_batch)
        shape = ((stacked,) + steps) if stacked else steps
        sharding = NamedSharding(
            loader.mesh,
            P(None, None, runtime.DATA_AXIS) if stacked
            else P(None, runtime.DATA_AXIS))
        return (_sds(shape, np.int32, sharding),
                _sds(shape, bool, sharding))

    def batch(loader):
        gb = loader.global_batch
        sh = loader.sharding
        imgs = loader.split.images
        return (_sds((gb,) + imgs.shape[1:], imgs.dtype, sh),
                _sds((gb,), loader.split.labels.dtype, sh),
                _sds((gb,), bool, sh))

    k = (min(cfg.epochs_per_dispatch, cfg.nb_epochs - start_epoch)
         if cfg.epochs_per_dispatch > 1 else 0)
    if (k > 1 and isinstance(train_loader, ResidentLoader)
            and isinstance(valid_loader, ResidentLoader)):
        # Chunked path: ONE fused program covers train+eval for K epochs.
        idx_tr, valid_tr = plan(train_loader, stacked=k)
        idx_va, valid_va = plan(valid_loader, stacked=k)
        keys = jnp.stack([utils.fold_key(root, start_epoch + i)
                          for i in range(k)])
        costs.record("train_epochs", engine.train_epochs.lower(
            state, train_loader.images, train_loader.labels, idx_tr,
            valid_tr, valid_loader.images, valid_loader.labels,
            idx_va, valid_va, keys).compile(), hlo=True)
    else:
        if isinstance(train_loader, ResidentLoader):
            idx_tr, valid_tr = plan(train_loader)
            costs.record("train_epoch", engine.train_epoch.lower(
                state, train_loader.images, train_loader.labels, idx_tr,
                valid_tr, key).compile(), hlo=True)
        else:
            img, lbl, vld = batch(train_loader)
            costs.record("train_step", engine.train_step.lower(
                state, img, lbl, vld, key).compile(), hlo=True)
        if isinstance(valid_loader, ResidentLoader):
            idx_va, valid_va = plan(valid_loader)
            costs.record("eval_epoch", engine.eval_epoch.lower(
                state, valid_loader.images, valid_loader.labels, idx_va,
                valid_va).compile(), hlo=True)
        else:
            img, lbl, vld = batch(valid_loader)
            costs.record("eval_step", engine.eval_step.lower(
                state, img, lbl, vld).compile(), hlo=True)
    warmup_s = time.perf_counter() - t0
    goodput.get().add("compile", warmup_s)
    hit = runtime.compilation_cache_hits() > hits_before
    tel.gauge("compile/warmup_s").set(warmup_s)
    tel.gauge("compile/cache_hit").set(1.0 if hit else 0.0)
    # Program size is the compile-time driver --scan-layers exists to
    # shrink: record the summed optimized-HLO instruction count of the
    # programs just compiled (per-program numbers live in costs.json).
    instrs = [e.get("hlo_instructions") for e in costs.registry().values()]
    instrs = [n for n in instrs if n is not None]
    if instrs:
        tel.gauge("compile/hlo_instructions").set(float(sum(instrs)))
    # Register the analytic per-sample count beside the XLA estimates so
    # both methodologies live in one costs.json, distinguishable by
    # ``source`` — and only the main process writes the shared file.
    fps = getattr(engine, "_flops_per_sample", None)
    if fps:
        costs.record_analytic("train_flops_per_sample",
                              flops_per_sample=fps,
                              note="engine jaxpr count (ops.flops); "
                                   "x global_batch for per-step")
    _, peak, pdt = _mfu_factors(engine)
    if peak:
        costs.record_mfu_denominator(peak, pdt,
                                     jax.devices()[0].device_kind)
    if runtime.is_main():
        costs.save(cfg.rsl_path)
        logging.info(f"AOT warmup: train/eval programs compiled in "
                     f"{warmup_s:.2f}s "
                     f"({'persistent-cache hit' if hit else 'cold'})")


def _run_eval_pass(engine: Engine, state, loader, epoch: int
                   ) -> tuple[float, float]:
    """One no-grad pass; returns globally-reduced (loss, accuracy).

    The whole pass is goodput ``compute``: eval batches come from an
    already-warm loader and the pass is dominated by dispatch; nested
    hooks (a retried read, a fault sleep) still claim their own
    categories out of the window (goodput.timed's non-overlap rule)."""
    tel = telemetry.get()
    with goodput.get().timed("compute"), \
            tel.span("eval_pass", epoch=epoch, steps=len(loader)):
        if isinstance(loader, ResidentLoader):
            idx, valid = loader.epoch_plan(epoch)
            totals = engine.eval_epoch(state, loader.images, loader.labels,
                                       idx, valid)
        else:
            totals = None
            for images, labels, valid in loader.epoch(epoch):
                m = engine.eval_step(state, images, labels, valid)
                totals = m if totals is None else jax.tree_util.tree_map(
                    jnp.add, totals, m)
        with runtime.sanctioned_host_transfer():  # per-epoch sync point
            totals = jax.device_get(totals)
    loss = float(totals["loss_numer"] / max(totals["loss_denom"], 1e-9))
    acc = float(totals["correct"] / max(totals["valid"], 1.0))
    return loss, acc


def _progress_logs(epoch: int, losses: np.ndarray) -> None:
    """The reference's every-10% in-epoch log lines (ref classif.py:63-68),
    with the mean correctly over i+1 batches (fixes SURVEY defect #9)."""
    nb_iters = len(losses)
    last_log = 0
    for i in range(nb_iters):
        n = i / nb_iters * 100
        if i and n // 10 > last_log:
            last_log = n // 10
            logging.info(f"\repoch:{epoch:03d} nb batches:{i + 1:04d} "
                         f"mean train loss:{losses[:i + 1].mean():.5f}")


def _run_train_pass(engine: Engine, state, loader, epoch: int, key
                    ) -> tuple[object, float, float]:
    """One optimization pass (ref processData train branch,
    classif.py:41-69), with the progress print + every-10% log."""
    nb_iters = len(loader)
    tel = telemetry.get()
    if isinstance(loader, ResidentLoader):
        # Whole epoch in one XLA dispatch; per-step metrics come back as
        # (steps,) arrays and the in-epoch log lines are emitted from them.
        # The telemetry span encloses the device_get, so its duration is
        # the real compute wall-clock, and the StepTraceAnnotation makes
        # the dispatch findable in a --profile trace by the same name.
        idx, valid = loader.epoch_plan(epoch)
        with goodput.get().timed("compute"), \
                jax.profiler.StepTraceAnnotation("train_dispatch",
                                                 step_num=epoch), \
                tel.span("train_dispatch", epoch=epoch, steps=nb_iters):
            state, metrics = engine.train_epoch(
                state, loader.images, loader.labels, idx, valid, key)
            with runtime.sanctioned_host_transfer():  # per-epoch sync
                metrics = jax.device_get(metrics)
        if runtime.is_main():
            _progress_logs(epoch, metrics["loss"])
        epoch_loss = float(np.mean(metrics["loss"]))
        epoch_acc = float(np.sum(metrics["correct"])
                          / max(np.sum(metrics["valid"]), 1.0))
        return state, epoch_loss, epoch_acc

    # Zero-sync accumulation (same design as the resident path): per-step
    # metric scalars stay on device for the whole epoch; ONE device_get at
    # the end feeds the every-10% log lines retroactively via
    # _progress_logs.  (Previously each 10% boundary called float() on a
    # device value — a blocking sync in the middle of the epoch.)
    #
    # Telemetry: the per-step histogram measures host-side dispatch time
    # (enqueue, not device completion — dispatch is async; the epoch-end
    # device_get absorbs the backlog).  Complementary to the pipeline's
    # data/wait_s counters: together they split host time into data wait
    # vs step dispatch.  The flight recorder (on by default) additionally
    # keeps the last N steps' total/wait/dispatch times + queue depth in
    # its ring, and drives the anomaly detector when --anomaly-capture is
    # set.  With BOTH disabled the off path runs the original loop with
    # zero added per-step work.
    rec = flightrec.get()
    gp = goodput.get()
    exporter = goodput.exporter()
    instrument = tel.enabled or rec.enabled or gp.enabled
    step_hist = tel.histogram("step/dispatch_s") if tel.enabled else None
    depth_fn = getattr(loader, "lookahead_depth", None)
    loss_hist, correct_hist, valid_hist = [], [], []
    prev_end = time.perf_counter() if instrument else 0.0
    gp.begin_steps()
    dispatch_s = 0.0
    for i, (images, labels, valid) in enumerate(loader.epoch(epoch)):
        if instrument:
            t0 = time.perf_counter()
            with jax.profiler.StepTraceAnnotation(
                    "train_step", step_num=epoch * nb_iters + i):
                state, metrics = engine.train_step(state, images, labels,
                                                   valid, key)
            dispatch_s = time.perf_counter() - t0
            if step_hist is not None:
                step_hist.observe(dispatch_s)
        else:
            state, metrics = engine.train_step(state, images, labels,
                                               valid, key)
        loss_hist.append(metrics["loss"])
        correct_hist.append(metrics["correct"])
        valid_hist.append(metrics["valid"])
        if runtime.is_main():
            print(f"\r{epoch:03d} {i / nb_iters * 100:.0f}%", end="\r")
        if instrument:
            end = time.perf_counter()
            # Goodput attribution for the step: dispatch -> compute,
            # inter-step wait -> data_wait (this is the ONLY place the
            # loader's blocking time is attributed — see pipeline.py).
            category = gp.step(dispatch_s, t0 - prev_end)
            if exporter is not None:
                exporter.note_step()
            # step_s spans yield-to-yield (wait + dispatch + host book-
            # keeping): the quantity the anomaly detector judges, since
            # a straggler can hide in any slice of it.
            flightrec.observe_step(
                rec, epoch=epoch, step=i, step_s=end - prev_end,
                dispatch_s=dispatch_s, wait_s=t0 - prev_end,
                queue_depth=(depth_fn(epoch) if depth_fn is not None
                             else None),
                category=category)
            prev_end = end
    gp.end_steps()
    with gp.timed("compute"), \
            runtime.sanctioned_host_transfer():  # ONE sync per epoch
        losses, corrects, valids = jax.device_get(
            jnp.stack([jnp.stack(loss_hist), jnp.stack(correct_hist),
                       jnp.stack(valid_hist)]))
    losses = np.asarray(losses)
    if runtime.is_main():
        _progress_logs(epoch, losses)
    epoch_loss = float(losses.mean())
    epoch_acc = float(np.sum(corrects) / max(float(np.sum(valids)), 1.0))
    return state, epoch_loss, epoch_acc


def _run_train_chunked(cfg: Config, engine: Engine, state, train_loader,
                       valid_loader, model_name: str, root, start_epoch: int,
                       best_valid_loss: float, start_time: float,
                       world: int, shutdown, saver=None) -> dict:
    """--epochs-per-dispatch > 1: K (train+valid) epochs per XLA dispatch.

    Per-epoch metrics and log lines are identical to the per-epoch path
    (the program returns per-epoch summaries); the trade-off is checkpoint
    cadence — only the chunk-final state exists on host, so the rolling
    checkpoint (and any best-model save) happens once per chunk.
    """
    history = []
    tel = telemetry.get()
    fps, peak, pdt = (_mfu_factors(engine) if tel.enabled
                      else (None, None, "bf16"))
    epoch = start_epoch
    while epoch < cfg.nb_epochs:
        chunk = list(range(epoch,
                           min(epoch + cfg.epochs_per_dispatch,
                               cfg.nb_epochs)))
        chunk_start = utils.monotonic()
        chunk_err = None
        try:
            idx_tr, valid_tr = train_loader.epoch_plan_many(chunk)
            idx_va, valid_va = valid_loader.epoch_plan_many(chunk)
            keys = jnp.stack([utils.fold_key(root, e) for e in chunk])
            # K fused epochs = ONE dispatch: the span (device_get
            # included) is the real compute wall-clock for the whole
            # chunk, annotated so --profile traces carry the same name.
            with goodput.get().timed("compute"), \
                    jax.profiler.StepTraceAnnotation("chunk_dispatch",
                                                     step_num=epoch), \
                    tel.span("chunk_dispatch", first_epoch=epoch,
                             epochs=len(chunk)):
                state, out = engine.train_epochs(
                    state, train_loader.images, train_loader.labels,
                    idx_tr, valid_tr, valid_loader.images,
                    valid_loader.labels, idx_va, valid_va, keys)
                with runtime.sanctioned_host_transfer():  # per-chunk sync
                    out = jax.device_get(out)
            end = utils.monotonic()

            per_epoch_s = (end - chunk_start) / len(chunk)
            train_samples = len(train_loader) * train_loader.global_batch
            sps_chip = train_samples / max(per_epoch_s, 1e-9) / world
            if tel.enabled:
                _record_throughput(tel, sps_chip, fps, peak, chunk[-1],
                                   peak_dtype=pdt)
            chunk_improved = False
            for k, e in enumerate(chunk):
                train_loss = float(np.mean(out["train_loss"][k]))
                train_acc = float(out["train_correct"][k]
                                  / max(out["train_valid"][k], 1.0))
                valid_loss = float(out["eval"]["loss_numer"][k]
                                   / max(out["eval"]["loss_denom"][k],
                                         1e-9))
                valid_acc = float(out["eval"]["correct"][k]
                                  / max(out["eval"]["valid"][k], 1.0))
                improved = valid_loss < best_valid_loss
                if runtime.is_main():
                    print(f"====================== epoch{e + 1:4d} "
                          f"======================")
                    _progress_logs(e, out["train_loss"][k])
                    epoch_mins, epoch_secs = utils.get_duration(
                        0, per_epoch_s)
                    mins, _ = utils.get_duration(start_time, end)
                    logging.info(
                        f"{'*' if improved else ' '} Epoch: {e + 1:03}  "
                        f"| Duration: {epoch_mins:03d}m {epoch_secs:02d}s"
                        f"  | Overall duration: {mins / 60:.2f}h")
                    logging.info(f"  Train       | Loss: {train_loss:.5f}"
                                 f"       | Acc: {train_acc * 100:.2f}%")
                    logging.info(f"  Validation  | Loss: {valid_loss:.5f}"
                                 f"       | Acc: {valid_acc * 100:.2f}%")
                    logging.info(f"  Throughput  | {sps_chip:,.0f} "
                                 f"samples/s/chip ({world} chip"
                                 f"{'s' if world > 1 else ''})")
                if improved:
                    best_valid_loss = valid_loss
                    chunk_improved = True
                history.append({"epoch": e, "train_loss": train_loss,
                                "train_acc": train_acc,
                                "valid_loss": valid_loss,
                                "valid_acc": valid_acc})

            last = chunk[-1]
            saveable = _saveable_state(cfg, state)
            _rotate_ckpt(cfg, saver, model_name, last)
            for prev in chunk[:-1]:  # rolling files from earlier chunks
                _rotate_ckpt(cfg, saver, model_name, prev)
            _save_ckpt(cfg,
                       ckpt.checkpoint_path(cfg.rsl_path, cfg.dataset,
                                            model_name, last),
                       model_name, saveable, last, best_valid_loss, saver)
            if chunk_improved:
                # Only the chunk-final state exists on host, so the best
                # file holds it (an approximation of the true best epoch
                # inside the chunk) — but it is written whenever ANY
                # epoch in the chunk improved, keeping the recorded
                # best_valid_loss and the best-model file in sync.
                _save_ckpt(cfg,
                           ckpt.best_model_path(cfg.rsl_path, cfg.dataset,
                                                model_name),
                           model_name, saveable, last, best_valid_loss,
                           saver)
            epoch = last + 1
        # Broad on purpose: ANY host-side failure (checkpoint I/O,
        # injected fault) must reach the SAME health allgather on every
        # rank — handling happens in _health_boundary.  Granularity is
        # the K-epoch chunk: one XLA dispatch cannot be interrupted
        # (documented trade-off of --epochs-per-dispatch).
        except Exception as e:
            chunk_err = e
        stop = _health_boundary(tel, shutdown, chunk[-1], chunk_err, cfg)
        goodput.get().reconcile(chunk[-1])
        if stop:
            break
    return {"history": history, "best_valid_loss": best_valid_loss,
            "model_name": model_name, "state": state,
            "preempted": shutdown.requested}


def run_train(cfg: Config) -> dict:
    """ref train() (classif.py:75-192), TPU-native."""
    # Before distributed init: the runtime.init retry/fault site must be
    # live for the initialize call itself.
    faults.configure(cfg.fault_plan, cfg.fault_seed, cfg.retry_max_attempts,
                     cfg.retry_base_delay, cfg.retry_timeout)
    join_info = None
    if cfg.elastic_join:
        if not cfg.elastic:
            raise ValueError(
                "--elastic-join requires --elastic: a joiner becomes a "
                "normal elastic member and must keep reconfiguring with "
                "its world")
        join_info = runtime.join_distributed(
            cfg.elastic_dir or elastic.default_elastic_dir(cfg.rsl_path),
            timeout_s=cfg.elastic_join_wait)
    else:
        runtime.initialize_distributed(elastic=cfg.elastic)
    if cfg.elastic:
        # Parse the admission policy NOW: a malformed --elastic-target
        # must fail at launch, not at the first health boundary mid-run.
        elastic.evaluate_join_policy(1, [], cfg.elastic_target,
                                     cfg.elastic_min_world)
    utils.initialize_logging(cfg.rsl_path, cfg.log_file,
                             truncate=runtime.is_main())
    # After distributed init so the rank in the filename is the GLOBAL
    # process index (per-rank files are the multi-host contract).
    tel = telemetry.configure(cfg.rsl_path, cfg.telemetry)
    # Flight recorder + (opt-in) anomaly-triggered profiling: the ring
    # buffer is on by default — the black box must be recording BEFORE
    # anything goes wrong (flightrec.py).
    rec = flightrec.configure(cfg.rsl_path, cfg.flightrec,
                              rank=runtime.process_index(),
                              ring_size=cfg.flightrec_ring)
    if cfg.anomaly_capture:
        flightrec.attach_detector(
            rec,
            trace_dir=os.path.join(cfg.rsl_path, "anomaly_traces"),
            window=cfg.anomaly_window, mad_k=cfg.anomaly_mad_k,
            rel_factor=cfg.anomaly_rel_factor,
            min_excess_s=cfg.anomaly_min_excess,
            capture_steps=cfg.anomaly_capture_steps,
            max_captures=cfg.anomaly_max_captures)
    # Goodput ledger: on whenever telemetry is, and forced on by the live
    # exporter (the /metrics category totals come from it).  The exporter
    # itself binds port + rank so same-host ranks coexist; /healthz facts
    # are injected as callables to keep goodput.py stdlib-only.
    goodput.configure(cfg.rsl_path,
                      bool(cfg.telemetry or cfg.metrics_port),
                      rank=runtime.process_index(),
                      world=runtime.process_count())
    if cfg.metrics_port:
        goodput.start_exporter(cfg.metrics_port,
                               rank=runtime.process_index(),
                               world_size_fn=runtime.world_size,
                               generation_fn=elastic.generation)
    costs.reset()
    # Before the first jit compile, so every program of this run can be
    # served from / written to the persistent cache.
    runtime.configure_compilation_cache(cfg.compilation_cache_path())
    mesh = runtime.make_mesh(model_parallel=cfg.model_parallel,
                             seq_parallel=cfg.seq_parallel)
    world = runtime.world_size()
    tel.event("run_start", action="train", model=cfg.model_name,
              dataset=cfg.dataset, world=world,
              processes=runtime.process_count(),
              batch_per_replica=cfg.batch_size)
    if join_info is not None:
        # The joiner's birth certificate: names the generation it was
        # admitted into, marks its telemetry stream (which may be a
        # departed rank's file, reopened in append) as restarted from
        # this instant — the timeline merger cuts alignment here — and
        # tells report aggregation this rank appeared mid-run by
        # design, not by accident.
        tel.event("elastic/join", generation=join_info["generation"],
                  new_world=join_info["new_world"],
                  new_rank=join_info["new_rank"],
                  coordinator=join_info["coordinator"])
        tel.gauge("elastic/world_size").set(join_info["new_world"])
        tel.flush()
        flightrec.get().record_event("elastic_join",
                                     generation=join_info["generation"],
                                     new_world=join_info["new_world"])
    if runtime.is_main():
        logging.info(f"process: {runtime.process_index()}/"
                     f"{runtime.process_count()}, world size: {world}")
        logging.info(f"batch size: {cfg.batch_size}/replica "
                     f"({cfg.batch_size * world} global), "
                     f"prefetch: {cfg.prefetch}")
        runtime.check_devices()

    if cfg.use_pretrained and cfg.checkpoint_file:
        # use_pretrained must never silently no-op (pretrained.py contract);
        # on resume every weight comes from the checkpoint, so the combined
        # request is a contradiction, not an ignorable flag.  Checked
        # before the checkpoint file is ever read: the conflict is real
        # whether or not the file exists.
        raise ValueError(
            "--use-pretrained cannot be combined with -f/--file resume: "
            "all weights come from the checkpoint")

    # Model name: resume reads it from the checkpoint (fixes SURVEY defect
    # #3 — ref classif.py:93 calls a misspelled helper and crashes).
    if cfg.checkpoint_file:
        try:
            model_name = ckpt.get_checkpoint_model_name(
                cfg.checkpoint_file)
        except ValueError as e:
            # A torn/corrupt head must not kill the restart: the lineage
            # fallback below recovers the STATE from an earlier snapshot;
            # the model name then comes from --model (loudly, since a
            # mismatched --model still fails at restore with a clear
            # template error).
            logging.warning(f"cannot read model name from "
                            f"{cfg.checkpoint_file!r} ({e}); using "
                            f"--model {cfg.model_name}")
            model_name = cfg.model_name
    else:
        model_name = cfg.model_name

    if cfg.epochs_per_dispatch < 1:
        raise ValueError(
            f"--epochs-per-dispatch must be >= 1, got "
            f"{cfg.epochs_per_dispatch}")
    if cfg.grad_accum < 1 or cfg.batch_size % cfg.grad_accum:
        raise ValueError(
            f"--grad-accum must be >= 1 and divide the per-replica batch "
            f"size ({cfg.batch_size}); got {cfg.grad_accum}")
    _validate_precision(cfg)
    vit_features = (cfg.attention != "full" or cfg.tensor_parallel
                    or cfg.pipeline_parallel)
    # ring x pipeline is the one SUPPORTED composition (3-D mesh,
    # --seq-parallel >= 2; vit_pipeline.make_pipeline_fn(ring=True))
    ring_pp = (cfg.pipeline_parallel and cfg.attention == "ring"
               and cfg.seq_parallel >= 2)
    exclusive = sum((cfg.attention != "full", cfg.tensor_parallel,
                     cfg.pipeline_parallel)) > 1 and not ring_pp
    needs_axis = (cfg.attention in ("ring", "ring_flash")
                  or cfg.tensor_parallel or cfg.pipeline_parallel)
    if vit_features and (model_name != "vit" or exclusive
                         or (needs_axis and cfg.model_parallel < 2)):
        # the registry enforces this too; checking here fails the run
        # before the dataset load pays for a doomed configuration
        raise ValueError(
            "--attention ring/flash/ring_flash, --tensor-parallel and "
            "--pipeline-parallel require --model vit, are mutually "
            "exclusive (except --pipeline-parallel + --attention ring "
            "with --seq-parallel >= 2), and (except single-chip flash) "
            "need --model-parallel >= 2; "
            f"got model={model_name!r}, "
            f"model_parallel={cfg.model_parallel}, "
            f"attention={cfg.attention!r}, "
            f"tensor_parallel={cfg.tensor_parallel}, "
            f"pipeline_parallel={cfg.pipeline_parallel}")
    if cfg.seq_parallel > 1 and not ring_pp:
        raise ValueError(
            "--seq-parallel >= 2 is the ring x pipeline composition's "
            "third mesh axis: it requires --pipeline-parallel with "
            "--attention ring (for plain sequence parallelism use "
            "--attention ring, which rings over the 'model' axis); got "
            f"seq_parallel={cfg.seq_parallel}, "
            f"attention={cfg.attention!r}, "
            f"pipeline_parallel={cfg.pipeline_parallel}")
    if cfg.pipeline_microbatches and not cfg.pipeline_parallel:
        raise ValueError(
            "--pipeline-microbatches requires --pipeline-parallel "
            "(it sets the GPipe M)")
    if cfg.moe_experts and (model_name != "vit" or cfg.tensor_parallel
                            or cfg.pipeline_parallel
                            or cfg.moe_experts < 2):
        # the registry enforces this too; fail before the dataset load
        raise ValueError(
            "--moe-experts needs --model vit, E >= 2, and is exclusive "
            "with --tensor-parallel/--pipeline-parallel; got "
            f"model={model_name!r}, moe_experts={cfg.moe_experts}, "
            f"tensor_parallel={cfg.tensor_parallel}, "
            f"pipeline_parallel={cfg.pipeline_parallel}")
    if (cfg.moe_experts and cfg.model_parallel >= 2
            and cfg.moe_experts % cfg.model_parallel):
        raise ValueError(
            f"--moe-experts {cfg.moe_experts} must be divisible by "
            f"--model-parallel {cfg.model_parallel} for expert "
            "parallelism (each device holds E/mp experts)")
    if cfg.pipeline_parallel:
        # The pipeline must actually engage: the per-data-shard batch the
        # MODEL sees has to hold >= M microbatch rows, else it would
        # degrade to the sequential schedule the user explicitly opted
        # out of.  batch_size is PER-REPLICA; the global batch
        # (batch * world) is sharded over world/model_parallel data
        # shards, so each shard sees batch * model_parallel rows — and
        # grad accumulation slices that by K again before the model
        # applies (engine.py stride-k microbatches).
        n_micro = cfg.pipeline_microbatches or cfg.model_parallel
        # exact division: the grad-accum check above already enforced
        # batch_size % grad_accum == 0 (so batch*mp is divisible too)
        # dp = world / (mp * sp), so each data shard sees
        # batch * mp * sp rows (sp = 1 on the 2-D mesh)
        b_local = (cfg.batch_size * cfg.model_parallel
                   * cfg.seq_parallel // cfg.grad_accum)
        if b_local < n_micro or b_local % n_micro:
            raise ValueError(
                f"--pipeline-parallel needs the per-data-shard batch "
                f"seen by the model (-b {cfg.batch_size} x "
                f"model_parallel {cfg.model_parallel} / grad_accum "
                f"{cfg.grad_accum} = {b_local}) to be a multiple of the "
                f"{n_micro} pipeline microbatches; raise -b or lower "
                f"--pipeline-microbatches/--grad-accum")
    _validate_ckpt_format(cfg)
    if cfg.use_pretrained:
        # Fail unsupported-arch / missing-path mistakes here, before the
        # dataset load and model init pay for a doomed run.
        pretrained.validate_request(model_name, cfg.pretrained_path)

    # Data path honored (fixes SURVEY defect #1).
    dataset = load_dataset(cfg.dataset, cfg.data_path, cfg.seed,
                           debug=cfg.debug, log=runtime.is_main(),
                           synthetic_fallback=cfg.synthetic_fallback)
    train_loader = _make_loader(cfg, dataset.splits["train"], mesh,
                                shuffle=True)
    # Eval splits are NOT shuffled: the reference shuffles its valid/test
    # samplers too (ref dataloader.py:151-152), but with globally-reduced
    # metrics a permutation is pure wasted work — retired per the repo's
    # fix-reference-defects policy (SURVEY defect #8/#9 family).
    valid_loader = _make_loader(cfg, dataset.splits["valid"], mesh,
                                shuffle=False)

    # Degrade mode: a background-writer failure downgrades the run to
    # synchronous saves (loud log + ckpt_async_degraded event) instead of
    # killing a healthy training loop at the next join.
    saver = (ckpt.AsyncSaver(on_error="degrade")
             if cfg.ckpt_async else None)
    start_time = utils.monotonic()
    shutdown = utils.GracefulShutdown()
    resume_file = cfg.checkpoint_file
    if join_info is not None and not resume_file:
        # A joiner resumes from the newest lineage-verified snapshot of
        # the run it joined — the same file its new peers restore after
        # their grow reconfigure (both sides land on the same epoch).
        resume_file = ckpt.newest_checkpoint(cfg.rsl_path, cfg.dataset,
                                             model_name)
    reconfigures = 0
    try:
        with shutdown:
            # The elastic retraining loop: one iteration per collective
            # world.  Without --elastic a WorldChangedError is never
            # raised and this runs the body exactly once, as before.
            while True:
                try:
                    return _train_world(cfg, model_name, dataset, mesh,
                                        train_loader, valid_loader,
                                        resume_file, start_time, shutdown,
                                        saver)
                except elastic.WorldChangedError as e:
                    grow = bool(getattr(e, "grow", False))
                    reconfigures += 1
                    if reconfigures > cfg.max_reconfigures:
                        raise faults.PeerFailureError(
                            f"world changed {reconfigures} times, over "
                            f"the --max-reconfigures {cfg.max_reconfigures}"
                            " cap; exiting with the last failure") from e
                    # Release everything that pins the old backend —
                    # the exception chain's tracebacks (their frames
                    # hold the old world's state/batches), the mesh,
                    # and the loaders' device handles — so the
                    # reconfigure below can destroy it.  Destruction
                    # closes our gloo sockets, the only wake-up signal
                    # a peer still blocked in a collective on the dead
                    # world ever gets (elastic.py module doc).
                    exc = e
                    while exc is not None:
                        exc.__traceback__ = None
                        exc = exc.__cause__ or exc.__context__
                    mesh = None
                    if isinstance(train_loader, ShardedLoader):
                        train_loader.release()
                        valid_loader.release()
                    else:  # resident loaders ARE device arrays; rebuilt
                        train_loader = valid_loader = None
                # Reconfigure OUTSIDE the except block: the interpreter
                # exception state (sys.exc_info) holds the traceback
                # until the block exits, defeating the release above.
                # The whole park -> rendezvous -> reinit -> reshard
                # sequence is goodput elastic_reconfigure (the restore
                # itself lands in ckpt_blocking inside _train_world).
                with goodput.get().timed("elastic_reconfigure"):
                    mesh = _elastic_reconfigure(cfg, tel, saver, grow)
                    if isinstance(train_loader, ShardedLoader):
                        # Deterministic reshard: same split/settings,
                        # re-derived rank slices for the new world.
                        train_loader = train_loader.reshard(mesh)
                        valid_loader = valid_loader.reshard(mesh)
                    else:  # resident loaders re-place onto the new mesh
                        train_loader = _make_loader(
                            cfg, dataset.splits["train"], mesh,
                            shuffle=True)
                        valid_loader = _make_loader(
                            cfg, dataset.splits["valid"], mesh,
                            shuffle=False)
                    # Resume from the newest lineage-verified snapshot;
                    # None (died before the first save) restarts from
                    # initialization — same as a fresh launch.
                    resume_file = ckpt.newest_checkpoint(
                        cfg.rsl_path, cfg.dataset, model_name)
    finally:
        # Join pending background checkpoint writes FIRST (their spans
        # must land before the close below; a preempted/finished run must
        # not exit with a half-written rolling file), then emit the
        # counter/histogram summaries — also on an exception path, so a
        # killed run still leaves a readable telemetry trail.
        try:
            if saver is not None:
                saver.close()
        finally:
            # Flight-record dump BEFORE the telemetry close so a crash
            # leaves both trails; sys.exc_info distinguishes the crash
            # dump from the ordinary end-of-run one.
            flightrec.get().close(
                "crash" if sys.exc_info()[0] is not None else "run_end")
            # Exporter down before the ledger closes (a scrape must not
            # see a half-final ledger), then the final reconcile + write.
            goodput.stop_exporter()
            goodput.get().close()
            tel.close()
            runtime.reset_compilation_cache()


def _train_world(cfg: Config, model_name: str, dataset: Dataset, mesh,
                 train_loader, valid_loader, resume_file, start_time,
                 shutdown, saver) -> dict:
    """Build engine+state for ONE collective world and train to the end.

    Everything here is world-shaped — engine (mesh-aware models), state
    placement, the epoch driver — so the elastic loop in ``run_train``
    can rerun it wholesale after a shrink.  ``resume_file`` is the
    -f/--file argument on the first world and the newest rolling
    snapshot after a reconfigure (None = fresh init, including the
    --use-pretrained path).
    """
    tel = telemetry.get()
    world = runtime.world_size()
    use_chunks = (cfg.epochs_per_dispatch > 1
                  and isinstance(train_loader, ResidentLoader)
                  and isinstance(valid_loader, ResidentLoader))
    if cfg.epochs_per_dispatch > 1 and not use_chunks:
        raise ValueError(
            "--epochs-per-dispatch > 1 requires device-resident data "
            "(whole epochs are fused into one XLA program); this run is "
            "streaming — drop --data-mode stream or lower the corpus size "
            "below --resident-max-bytes")

    engine = _build_engine(cfg, model_name, dataset, len(train_loader),
                           mesh=mesh)
    # The resolved policy is part of the run's record: the precision gate
    # (scripts/precision_gate.py) reads this event back to assert the
    # accumulators really are f32 under the half-precision presets.
    tel.event("precision_policy", remat=cfg.remat,
              grad_accum=cfg.grad_accum,
              **engine.precision.describe())
    root = utils.root_key(cfg.seed)
    state = engine.init_state(root)

    if resume_file:
        if os.path.isdir(resume_file):
            # orbax: place the template FIRST so the restore lands
            # straight in the final (possibly model-sharded) layout —
            # no transient fully-replicated copy of a state that may
            # only fit sharded (checkpoint.py leaf_target).
            state = _place_state(state, mesh, cfg)
        # Lineage-aware resume: a torn/corrupt head checkpoint falls back
        # (loudly) to the newest snapshot that verifies, instead of
        # killing the restart loop on the very file a crash mangled.
        # Elastic resume rides the same path: snapshots are replicated
        # host state, so a file written by the LARGER world restores
        # bit-identically here (ckpt.newest_checkpoint).
        state, start_epoch, best_valid_loss = \
            ckpt.load_checkpoint_with_fallback(
                resume_file, state, cfg.rsl_path, cfg.dataset,
                model_name)
        state = _place_state(state, mesh, cfg)
    else:
        if cfg.use_pretrained:
            # Backbone from a user-provided torchvision state_dict, fresh
            # head — the reference's replace-head-after-load fine-tuning
            # init (ref utils.py:38-105, config.py:51).  Raises for
            # unsupported archs or a missing file; never a silent no-op.
            params, batch_stats = pretrained.load_pretrained(
                model_name, cfg.pretrained_path, state.params,
                state.batch_stats)
            state = state.replace(params=params, batch_stats=batch_stats)
            if runtime.is_main():
                logging.info(f"pretrained backbone loaded from "
                             f"{cfg.pretrained_path}")
        state = _place_state(state, mesh, cfg)
        start_epoch, best_valid_loss = 0, float("inf")

    if cfg.elastic and elastic.generation() > 0:
        # Post-reconfigure resume point: which epoch this generation's
        # world picked up from.  The chaos grow gate reads this back to
        # locate the snapshot an uninterrupted reference must share.
        tel.event("elastic/resume", generation=elastic.generation(),
                  epoch=start_epoch, world=world)
        tel.flush()

    if cfg.aot_warmup:
        _aot_warmup(cfg, engine, state, train_loader, valid_loader, root,
                    start_epoch)

    if use_chunks:
        return _run_train_chunked(cfg, engine, state, train_loader,
                                  valid_loader, model_name, root,
                                  start_epoch, best_valid_loss,
                                  start_time, world, shutdown, saver)
    return _run_train_epochs(cfg, engine, state, train_loader,
                             valid_loader, model_name, root,
                             start_epoch, best_valid_loss,
                             start_time, world, shutdown, saver)


def _elastic_reconfigure(cfg: Config, tel, saver, grow: bool = False,
                         purpose: str = "train"):
    """Shrink into the surviving world — or grow into the admitted one —
    and return the new mesh.

    Sequence (each step's rationale in elastic.py): drain pending async
    checkpoint writes (the newest snapshot is what the new world resumes
    from), dump the flight recorder (the departed rank's last minutes
    are the post-mortem; on a grow, the pre-grow world's record), then
    rendezvous + re-init the collective runtime and rebuild the mesh
    against the new backend.  Telemetry keeps the ORIGINAL rank file —
    stable per-process streams are what the timeline merger aligns on
    across the reconfigure boundary.
    """
    if saver is not None:
        try:
            saver.wait()
        except Exception as e:
            # A failed background save must not block the reconfigure:
            # lineage verification skips the bad file on restore.
            logging.error(f"async checkpoint flush failed during "
                          f"reconfigure (continuing): {e}")
    flightrec.get().dump("reconfigure")
    old_rank = runtime.process_index()
    old_world = runtime.process_count()
    elastic_dir = cfg.elastic_dir or elastic.default_elastic_dir(
        cfg.rsl_path)
    info = elastic.reconfigure(elastic_dir, old_rank, old_world,
                               grow=grow, target=cfg.elastic_target,
                               min_world=cfg.elastic_min_world,
                               purpose=purpose)
    tel.event("elastic/reconfigure", generation=info["generation"],
              old_world=old_world, new_world=info["new_world"],
              old_rank=old_rank, new_rank=info["new_rank"],
              grow=grow, joined=info.get("joiners", []),
              coordinator=info["coordinator"], purpose=purpose)
    tel.gauge("elastic/world_size").set(info["new_world"])
    tel.flush()
    flightrec.get().record_event("elastic_reconfigure",
                                 generation=info["generation"],
                                 new_world=info["new_world"])
    return runtime.make_mesh(model_parallel=cfg.model_parallel,
                             seq_parallel=cfg.seq_parallel)


def _peer_loss_exit(tel, epoch: int, err, elastic_on: bool):
    """A peer is GONE — dead transport mid-collective or a timed-out
    health agreement.  Under --elastic this is the reconfigure signal;
    otherwise it is the pre-elastic coordinated exit, minus the hang.
    Always raises."""
    tel.event("peer_loss", epoch=epoch, elastic=elastic_on,
              error=repr(err))
    tel.flush()
    if elastic_on:
        # No flight dump here: _elastic_reconfigure dumps with reason
        # "reconfigure" once the shrink actually starts.
        raise elastic.WorldChangedError(
            f"peer lost during epoch {epoch + 1}: {err}") from err
    flightrec.get().dump("peer_failure")
    raise faults.PeerFailureError(
        f"a peer process vanished during epoch {epoch + 1} ({err}); "
        "exiting") from err


def _health_boundary(tel, shutdown, epoch: int, err, cfg=None) -> bool:
    """Epoch/chunk-boundary failure agreement.  ONE allgather carries
    the fatal flag, the shutdown flag, and the elastic grow vote
    (runtime.agree_health), so the collective schedule on healthy ranks
    is unchanged from the old shutdown-only check.  A rank that failed
    host-side re-raises its own error; its peers raise PeerFailureError
    — every rank exits together, none hangs waiting in a later
    collective.  Under --elastic a peer VANISHING (vs failing and
    reporting) becomes WorldChangedError — the signal for run_train's
    elastic loop to shrink and resume — and --health-timeout bounds the
    agreement itself so a dead peer that never reaches this boundary
    yields a local verdict instead of a deadlock.  An admissible join
    claim in the rendezvous dir (scanned just before the allgather)
    becomes WorldChangedError with ``grow=True`` — same loop, larger
    world.  Failure and preemption outrank a grow: a claim seen at a
    failing boundary stays pending for the shrunken world's next one.
    Returns True when the run should stop cleanly (preemption)."""
    elastic_on = bool(cfg is not None and cfg.elastic)
    tel.flush()  # boundary: buffered events hit the disk
    if elastic.is_peer_loss(err):
        # The epoch itself died INSIDE a collective: the transport to
        # the dead peer is gone, so the agreement allgather below would
        # ride the same broken channel.  The local error is the verdict.
        _peer_loss_exit(tel, epoch, err, elastic_on)
    admit_ids = _scan_grow(cfg, tel, epoch) if elastic_on else []
    timeout_s = (cfg.health_timeout if cfg is not None else 0.0) or None
    try:
        # The allgather's duration IS the straggler wait: every rank
        # blocks here until the slowest arrives (goodput collective_skew).
        with goodput.get().timed("collective_skew"):
            any_failed, any_shutdown, any_grow = runtime.agree_health(
                err is not None, shutdown.requested, timeout_s=timeout_s,
                grow=bool(admit_ids))
    except faults.HealthTimeoutError as timeout_err:
        # Bounded failure detection: the peer died BETWEEN collectives
        # and never reached this boundary — without the bound the
        # allgather blocks forever on it.
        tel.event("health_timeout", epoch=epoch, timeout_s=timeout_s)
        tel.flush()
        if err is not None:
            raise err  # the local failure outranks the missing peer
        _peer_loss_exit(tel, epoch, timeout_err, elastic_on)
    # Broad on purpose: the transport surfaces a dead peer as ValueError
    # (gloo) but backend wrappers vary; anything non-peer-loss re-raises.
    except Exception as agree_err:
        if err is None and elastic.is_peer_loss(agree_err):
            # The agreement's own transport hit the dead peer first.
            _peer_loss_exit(tel, epoch, agree_err, elastic_on)
        raise err if err is not None else agree_err
    # The allgather above returns at (nearly) the same real instant on
    # every rank, so this event's paired ts+mono stamps are the timeline
    # merger's cross-rank clock-alignment points (timeline.py).
    tel.event("health_boundary", epoch=epoch)
    if any_failed:
        # Loud on EVERY rank: each process's JSONL records who noticed
        # and why before the coordinated exit — never a silent death.
        tel.event("peer_failure", epoch=epoch, local=err is not None,
                  error=repr(err) if err is not None else None)
        tel.flush()
        # The healthy ranks' black box is the post-mortem: what were the
        # minutes before the peer died doing?  Dump it now, before the
        # coordinated exit unwinds.
        flightrec.get().dump("peer_failure")
        if err is not None:
            raise err
        if elastic_on:
            # The failed rank reported, agreed, and is exiting; the
            # healthy remainder reconfigures around the hole it leaves.
            raise elastic.WorldChangedError(
                f"a peer reported failure during epoch {epoch + 1}")
        raise faults.PeerFailureError(
            f"a peer process failed during epoch {epoch + 1}; exiting "
            "with it (health agreement)")
    if any_shutdown:
        shutdown.requested = True
        tel.event("preempt", after_epoch=epoch)
        if runtime.is_main():
            logging.info(f"preempted after epoch {epoch + 1}: "
                         f"checkpoint written, resume with -f")
        return True
    if any_grow and elastic_on:
        # Every rank agreed (one vote suffices — the OR repairs the
        # filesystem-polling race): park this world and re-rendezvous
        # with the joiners included.  The rendezvous coordinator's
        # re-scan of the claims is the authoritative admission.
        tel.event("elastic/grow", epoch=epoch, joiners=admit_ids)
        tel.flush()
        raise elastic.WorldChangedError(
            f"join claim(s) admitted at the epoch {epoch + 1} boundary;"
            " growing the world", grow=True)
    return False


def _scan_grow(cfg, tel, epoch: int) -> list:
    """Health-boundary autoscaling poll: pending join claims through
    the --elastic-target policy.  Declines are answered here by the
    main rank — the claimant stops waiting and the world never pays a
    reconfigure window for them; admissions only raise this rank's grow
    vote for the agreement allgather.  Any filesystem hiccup skips the
    scan (the next boundary retries) rather than failing a healthy
    boundary."""
    elastic_dir = cfg.elastic_dir or elastic.default_elastic_dir(
        cfg.rsl_path)
    try:
        admit, declined = elastic.scan_joins(
            elastic_dir, runtime.process_count(), cfg.elastic_target,
            cfg.elastic_min_world)
        if declined and runtime.is_main():
            elastic.decline_joins(elastic_dir, declined,
                                  elastic.generation() + 1)
            for jid, reason in declined:
                tel.event("elastic/join_declined", epoch=epoch,
                          join_id=jid, reason=reason,
                          target=cfg.elastic_target,
                          min_world=cfg.elastic_min_world)
    except OSError as e:
        logging.warning(f"elastic: join scan failed at the epoch "
                        f"{epoch + 1} boundary (retrying next): {e}")
        return []
    if admit:
        tel.event("elastic/join_admit", epoch=epoch, joiners=admit,
                  target=cfg.elastic_target)
    return admit


def _run_train_epochs(cfg: Config, engine: Engine, state, train_loader,
                      valid_loader, model_name: str, root, start_epoch: int,
                      best_valid_loss: float, start_time: float, world: int,
                      shutdown, saver=None) -> dict:
    """The per-epoch driver loop (ref classif.py:151-192)."""
    history = []
    tel = telemetry.get()
    fps, peak, pdt = (_mfu_factors(engine) if tel.enabled
                      else (None, None, "bf16"))
    for epoch in range(start_epoch, cfg.nb_epochs):
        if runtime.is_main():
            print(f"====================== epoch{epoch + 1:4d} "
                  f"======================")
        epoch_start = utils.monotonic()

        epoch_err = None
        try:
            # SURVEY §5 tracing: trace the first post-compile epoch.
            # stop_trace lives in the finally: an epoch that raises must
            # not leak a running profiler into the next epoch's
            # start_trace (graftlint profiler-trace-leak).
            tracing = cfg.profile and epoch == start_epoch + 1
            if tracing:
                jax.profiler.start_trace(f"{cfg.rsl_path}/trace")
            try:
                epoch_key = utils.fold_key(root, epoch)
                with tel.span("epoch", epoch=epoch):
                    with tel.span("train_pass", epoch=epoch,
                                  steps=len(train_loader)):
                        state, train_loss, train_acc = _run_train_pass(
                            engine, state, train_loader, epoch, epoch_key)
                    train_end = utils.monotonic()
                    valid_loss, valid_acc = _run_eval_pass(
                        engine, state, valid_loader, epoch)
            finally:
                if tracing:
                    jax.profiler.stop_trace()
                    if runtime.is_main():
                        logging.info(f"profiler trace written to "
                                     f"{cfg.rsl_path}/trace")
                        # Auto-attribute the fresh trace: a --profile
                        # run leaves roofline.json + a 'roofline'
                        # telemetry event beside the raw capture, so
                        # op-level blame never requires a second
                        # command.  Advisory: analysis failure must not
                        # fail the epoch.
                        try:
                            from . import roofline

                            rep = roofline.analyze(
                                f"{cfg.rsl_path}/trace",
                                rsl_path=cfg.rsl_path)
                            roofline.save_report(rep, cfg.rsl_path)
                            roofline.emit_telemetry(rep, tel)
                            logging.info(
                                f"roofline: {rep['coverage'] * 100:.1f}%"
                                f" of step time attributed to "
                                f"{rep['n_ops']} ops (top: "
                                f"{rep['ops'][0]['name']})")
                        except Exception as e:
                            # advisory post-run analysis: a torn trace
                            # or parse bug must never fail the run
                            logging.warning(
                                f"roofline analysis skipped: {e}")

            end = utils.monotonic()
            epoch_mins, epoch_secs = utils.get_duration(epoch_start, end)
            mins, _secs = utils.get_duration(start_time, end)
            train_samples = len(train_loader) * train_loader.global_batch
            sps_chip = (train_samples
                        / max(train_end - epoch_start, 1e-9) / world)
            if tel.enabled:
                _record_throughput(tel, sps_chip, fps, peak, epoch,
                                   peak_dtype=pdt)

            # Update best BEFORE any checkpoint write so the rolling file
            # carries the post-epoch best; saving it first would make a
            # resume from an improving epoch restore a stale
            # best_valid_loss.
            improved = valid_loss < best_valid_loss
            if improved:
                best_valid_loss = valid_loss
            saveable = _saveable_state(cfg, state)
            if runtime.is_main():  # ref classif.py:176-192
                logging.info(
                    f"{'*' if improved else ' '} Epoch: {epoch + 1:03}  "
                    f"| Duration: {epoch_mins:03d}m {epoch_secs:02d}s  "
                    f"| Overall duration: {mins / 60:.2f}h")
                logging.info(f"  Train       | Loss: {train_loss:.5f}     "
                             f"  | Acc: {train_acc * 100:.2f}%")
                logging.info(f"  Validation  | Loss: {valid_loss:.5f}     "
                             f"  | Acc: {valid_acc * 100:.2f}%")
                # North-star metric surfaced per epoch (BASELINE.md).
                logging.info(f"  Throughput  | {sps_chip:,.0f} "
                             f"samples/s/chip "
                             f"({world} chip{'s' if world > 1 else ''})")
            _rotate_ckpt(cfg, saver, model_name, epoch)
            _save_ckpt(cfg,
                       ckpt.checkpoint_path(cfg.rsl_path, cfg.dataset,
                                            model_name, epoch),
                       model_name, saveable, epoch, best_valid_loss, saver)
            if improved:
                _save_ckpt(cfg,
                           ckpt.best_model_path(cfg.rsl_path, cfg.dataset,
                                                model_name),
                           model_name, saveable, epoch, best_valid_loss,
                           saver)
            history.append({"epoch": epoch, "train_loss": train_loss,
                            "train_acc": train_acc,
                            "valid_loss": valid_loss,
                            "valid_acc": valid_acc})
        # Broad on purpose: ANY host-side failure (data pipeline,
        # checkpoint I/O, injected fault) must reach the SAME health
        # allgather on every rank — handling happens in _health_boundary.
        except Exception as e:
            epoch_err = e
        stop = _health_boundary(tel, shutdown, epoch, epoch_err, cfg)
        # Epoch-boundary reconciliation AFTER the health allgather so the
        # window includes its collective_skew; the unattributed remainder
        # becomes an explicit "other" row entry (goodput.py contract).
        goodput.get().reconcile(epoch)
        if stop:
            break
    # Final state is returned so callers (multi-process tests, notebooks)
    # can inspect the trained parameters without re-reading a checkpoint.
    return {"history": history, "best_valid_loss": best_valid_loss,
            "model_name": model_name, "state": state,
            "preempted": shutdown.requested}


def run_test(cfg: Config) -> dict:
    """ref test() (classif.py:197-243), TPU-native."""
    if cfg.use_pretrained:
        # Same never-silently-no-op contract as run_train: eval weights
        # come from -f FILE, so the flag is a contradiction here.
        raise ValueError(
            "--use-pretrained is not applicable to the test subcommand: "
            "weights come from -f FILE")
    _validate_ckpt_format(cfg)
    # Same --seq-parallel composition guard run_train enforces (ADVICE #3):
    # without it run_test builds a 3-D mesh for ANY --seq-parallel value,
    # silently shrinking data-parallel width for a non-ring eval.
    if cfg.seq_parallel > 1 and not (cfg.pipeline_parallel
                                     and cfg.attention == "ring"):
        raise ValueError(
            "--seq-parallel >= 2 is the ring x pipeline composition's "
            "third mesh axis: it requires --pipeline-parallel with "
            "--attention ring; got "
            f"seq_parallel={cfg.seq_parallel}, "
            f"attention={cfg.attention!r}, "
            f"pipeline_parallel={cfg.pipeline_parallel}")
    faults.configure(cfg.fault_plan, cfg.fault_seed, cfg.retry_max_attempts,
                     cfg.retry_base_delay, cfg.retry_timeout)
    runtime.initialize_distributed()
    # After distributed init: the f16-on-TPU check reads the backend.
    _validate_precision(cfg)
    utils.initialize_logging(cfg.rsl_path, cfg.log_file,
                             truncate=runtime.is_main())
    tel = telemetry.configure(cfg.rsl_path, cfg.telemetry)
    flightrec.configure(cfg.rsl_path, cfg.flightrec,
                        rank=runtime.process_index(),
                        ring_size=cfg.flightrec_ring)
    goodput.configure(cfg.rsl_path, cfg.telemetry,
                      rank=runtime.process_index(),
                      world=runtime.process_count())
    runtime.configure_compilation_cache(cfg.compilation_cache_path())
    mesh = runtime.make_mesh(model_parallel=cfg.model_parallel,
                             seq_parallel=cfg.seq_parallel)
    tel.event("run_start", action="test", dataset=cfg.dataset,
              world=runtime.world_size(),
              processes=runtime.process_count(),
              batch_per_replica=cfg.batch_size)
    if runtime.is_main():
        logging.info(f"process: {runtime.process_index()}/"
                     f"{runtime.process_count()}, world size: "
                     f"{runtime.world_size()}")

    model_name = ckpt.get_checkpoint_model_name(cfg.checkpoint_file)
    dataset = load_dataset(cfg.dataset, cfg.data_path, cfg.seed,
                           debug=cfg.debug, log=runtime.is_main(),
                           synthetic_fallback=cfg.synthetic_fallback)
    # Unshuffled (see run_train's valid_loader note; ref quirk retired).
    test_loader = _make_loader(cfg, dataset.splits["test"], mesh,
                               shuffle=False)

    engine = _build_engine(cfg, model_name, dataset, len(test_loader),
                           mesh=mesh)
    template = engine.init_state(utils.root_key(cfg.seed))
    if os.path.isdir(cfg.checkpoint_file):
        # orbax: restore straight into the final layout (see run_train)
        template = _place_state(template, mesh, cfg)
    state, _, _ = ckpt.load_checkpoint(cfg.checkpoint_file, template,
                                       restore_optimizer=False)
    state = _place_state(state, mesh, cfg)

    start_time = utils.monotonic()
    try:
        loss, acc = _run_eval_pass(engine, state, test_loader, epoch=0)
    finally:
        flightrec.get().close(
            "crash" if sys.exc_info()[0] is not None else "run_end")
        goodput.get().close()
        tel.close()
        runtime.reset_compilation_cache()
    mins, secs = utils.get_duration(start_time, utils.monotonic())
    if runtime.is_main():  # ref classif.py:242-243
        logging.info(f"Time: {mins}m {secs}s, Acc: {acc * 100:.2f}%")
    return {"test_loss": loss, "test_acc": acc, "model_name": model_name}


def _serve_warmup(cfg: Config, engine: Engine, state, mesh, buckets,
                  sample_shape, sample_dtype) -> None:
    """AOT-compile the predict program for every bucket on the serving
    menu BEFORE the port answers its first request, so no request-path
    batch shape ever compiles.  Same contract as --aot-warmup: the time
    is a recorded ``compile`` goodput category (restart-to-first-
    response is bounded and attributed), each program's cost analysis
    lands in costs.json, and with the persistent compilation cache a
    replica restart turns the whole menu into disk hits."""
    tel = telemetry.get()
    hits_before = runtime.compilation_cache_hits()
    t0 = time.perf_counter()
    n_dev = int(mesh.devices.size)
    for b in buckets:
        # A bucket that divides over the local devices is served
        # sharded; the rest (b < n_dev, or indivisible) replicated —
        # the same rule the infer closure applies per batch.
        sh = (runtime.data_sharding(mesh) if b % n_dev == 0
              else runtime.replicated_sharding(mesh))
        costs.record(f"predict_b{b}", engine.predict_step.lower(
            state, _sds((b,) + tuple(sample_shape), sample_dtype,
                        sh)).compile(), hlo=True)
    warmup_s = time.perf_counter() - t0
    goodput.get().add("compile", warmup_s)
    hit = runtime.compilation_cache_hits() > hits_before
    tel.gauge("compile/warmup_s").set(warmup_s)
    tel.gauge("compile/cache_hit").set(1.0 if hit else 0.0)
    if runtime.is_main():
        costs.save(cfg.rsl_path)
    logging.info(f"serve: {len(buckets)} bucket programs "
                 f"({','.join(str(b) for b in buckets)}) compiled in "
                 f"{warmup_s:.2f}s "
                 f"({'persistent-cache hit' if hit else 'cold'})")


def _serve_build_replica(cfg: Config, model_name: str, dataset, buckets,
                         sample_shape, sample_dtype):
    """Build THIS replica's predict closure for the current elastic
    generation: local mesh -> engine -> lineage-verified restore (any
    params_layout) -> replicated placement -> per-bucket AOT warmup.
    Called at startup and again after every reconfigure — the rebuild
    re-restores the checkpoint and re-warms the menu (persistent-cache
    hits), so surviving a rank loss costs seconds, not a recompile."""
    mesh = runtime.make_serve_mesh()
    engine = _build_engine(cfg, model_name, dataset, steps_per_epoch=1,
                           mesh=mesh)
    template = engine.init_state(utils.root_key(cfg.seed))
    if os.path.isdir(cfg.checkpoint_file):
        # orbax: restore straight into the final layout (see run_train)
        template = _place_state(template, mesh, cfg)
    state, _epoch = ckpt.restore_for_serving(cfg.checkpoint_file,
                                             template)
    state = _place_state(state, mesh, cfg)
    _serve_warmup(cfg, engine, state, mesh, buckets, sample_shape,
                  sample_dtype)
    n_dev = int(mesh.devices.size)

    def infer(arr):
        sh = (runtime.data_sharding(mesh) if arr.shape[0] % n_dev == 0
              else runtime.replicated_sharding(mesh))
        labels, confs = engine.predict_step(state,
                                            jax.device_put(arr, sh))
        # The answer must leave the device — this is the one sanctioned
        # device->host read on the serving path.
        with runtime.sanctioned_host_transfer():
            return np.asarray(labels), np.asarray(confs)

    return infer


def run_serve(cfg: Config) -> dict:
    """``main.py serve``: batched, elastic inference from a checkpoint
    (ISSUE 15).  Setup mirrors run_test; the loop is serving/server.py's
    micro-batch driver wrapped in run_train's elastic-reconfigure shape:
    one iteration of the while loop per collective world."""
    from . import serving

    if cfg.use_pretrained:
        raise ValueError(
            "--use-pretrained is not applicable to the serve subcommand: "
            "weights come from -f FILE")
    if cfg.model_parallel > 1 or cfg.tensor_parallel \
            or cfg.pipeline_parallel or cfg.seq_parallel > 1:
        # Replicas shard at the REQUEST level over replica-local meshes
        # (runtime.make_serve_mesh): there are no cross-host collectives
        # to lay a model axis over.  Pipeline/scan-trained checkpoints
        # still serve — the restore converts them to the plain layout.
        raise ValueError(
            "serve runs replica-local data-parallel inference; "
            "--model-parallel/--tensor-parallel/--pipeline-parallel/"
            "--seq-parallel do not apply (model-parallel-trained "
            "checkpoints convert at load)")
    buckets = serving.parse_buckets(cfg.serve_buckets)
    if cfg.serve_queue < max(buckets):
        raise ValueError(
            f"--serve-queue {cfg.serve_queue} is smaller than the "
            f"largest bucket {max(buckets)}: the queue could never "
            "fill a full batch")
    _validate_ckpt_format(cfg)
    faults.configure(cfg.fault_plan, cfg.fault_seed,
                     cfg.retry_max_attempts, cfg.retry_base_delay,
                     cfg.retry_timeout)
    join_info = None
    if cfg.elastic_join:
        if not cfg.elastic:
            raise ValueError(
                "--elastic-join requires --elastic: a joining replica "
                "becomes a normal elastic member and must keep "
                "reconfiguring with its world")
        join_info = runtime.join_distributed(
            cfg.elastic_dir or elastic.default_elastic_dir(cfg.rsl_path),
            timeout_s=cfg.elastic_join_wait)
    else:
        runtime.initialize_distributed(elastic=cfg.elastic)
    if cfg.elastic:
        elastic.evaluate_join_policy(1, [], cfg.elastic_target,
                                     cfg.elastic_min_world)
    _validate_precision(cfg)
    utils.initialize_logging(cfg.rsl_path, cfg.log_file,
                             truncate=runtime.is_main())
    # Telemetry is ALWAYS on in serve mode: the latency histograms and
    # queue gauges are the tier's operational surface (/metrics renders
    # only enabled telemetry), not an opt-in debugging aid.
    tel = telemetry.configure(cfg.rsl_path, True)
    # Request tracing is always on in serve mode, same rationale: the
    # per-request span chain (trace-rank<N>.jsonl) is the tier's
    # incident surface, not an opt-in debugging aid.
    tracing.configure(cfg.rsl_path, True, rank=runtime.process_index())
    flightrec.configure(cfg.rsl_path, cfg.flightrec,
                        rank=runtime.process_index(),
                        ring_size=cfg.flightrec_ring)
    goodput.configure(cfg.rsl_path, True,
                      rank=runtime.process_index(),
                      world=runtime.process_count())
    if cfg.metrics_port:
        goodput.start_exporter(cfg.metrics_port,
                               rank=runtime.process_index(),
                               world_size_fn=runtime.world_size,
                               generation_fn=elastic.generation)
    costs.reset()
    runtime.configure_compilation_cache(cfg.compilation_cache_path())
    # Bound once from the INITIAL rank and kept for the process
    # lifetime: ranks renumber at every reconfigure, and a port that
    # moved with them would break every client mid-incident.
    port = cfg.serve_port + runtime.process_index()
    # Accepted connections on the replica's own listeners must survive
    # the elastic park's stale-socket sweep, or every in-flight request
    # dies at each reconfigure.
    elastic.register_app_ports(
        port, (cfg.metrics_port + runtime.process_index())
        if cfg.metrics_port else 0)
    tel.event("run_start", action="serve", dataset=cfg.dataset,
              world=runtime.world_size(),
              processes=runtime.process_count(),
              buckets=list(buckets), port=port)
    if join_info is not None:
        tel.event("elastic/join", generation=join_info["generation"],
                  new_world=join_info["new_world"],
                  new_rank=join_info["new_rank"],
                  coordinator=join_info["coordinator"])
        tel.gauge("elastic/world_size").set(join_info["new_world"])
        tel.flush()
    logging.info(f"serve: process {runtime.process_index()}/"
                 f"{runtime.process_count()}, replica port {port}")

    model_name = ckpt.get_checkpoint_model_name(cfg.checkpoint_file)
    dataset = load_dataset(cfg.dataset, cfg.data_path, cfg.seed,
                           debug=cfg.debug, log=runtime.is_main(),
                           synthetic_fallback=cfg.synthetic_fallback)
    images = dataset.splits["test"].images
    sample_shape, sample_dtype = images.shape[1:], images.dtype

    shutdown = utils.GracefulShutdown()
    tier = None
    reconfigures = 0
    try:
        with shutdown:
            infer = _serve_build_replica(cfg, model_name, dataset,
                                         buckets, sample_shape,
                                         sample_dtype)
            tier = serving.ServingTier(
                infer, sample_shape, sample_dtype, buckets,
                max_queue=cfg.serve_queue,
                max_latency_s=cfg.serve_max_latency_ms / 1000.0,
                port=port,
                request_timeout_s=cfg.serve_request_timeout,
                max_requests=cfg.serve_max_requests)
            # Served-model identity (ISSUE 19): the lineage sha rides
            # /livez, the exporter /healthz serve block, and every
            # trace record — what the front door's canary verdict
            # compares.  current_ckpt tracks hot-swaps so an elastic
            # rebuild re-restores what is actually being served.
            current_ckpt = [cfg.checkpoint_file]
            tier.set_checkpoint(ckpt.lineage_info(cfg.checkpoint_file))
            tracing.get().set_lineage(
                (tier.checkpoint or {}).get("sha256"))

            def swap_fn(path):
                # the /admin/reload seam: lineage-verify, rebuild the
                # predict closure (restore_for_serving + AOT warmup),
                # hand it back to the driver loop
                new_name = ckpt.get_checkpoint_model_name(path)
                if new_name != model_name:
                    raise ValueError(
                        f"checkpoint {path!r} holds model "
                        f"{new_name!r}; this replica serves "
                        f"{model_name!r}")
                reason = ckpt.verify_checkpoint(path)
                if reason is not None:
                    raise ValueError(
                        f"lineage verification failed for {path!r}: "
                        f"{reason}")
                new_infer = _serve_build_replica(
                    cfg.replace(checkpoint_file=path), model_name,
                    dataset, buckets, sample_shape, sample_dtype)
                current_ckpt[0] = path
                return new_infer, ckpt.lineage_info(path)

            tier.set_swap_fn(swap_fn)
            goodput.set_health_extra(tier.stats)
            tier.start()

            def health_fn():
                # The training health boundary verbatim: ONE allgather
                # for failure + preemption + grow votes, peer-loss ->
                # WorldChangedError under --elastic, True on clean stop.
                return _health_boundary(tel, shutdown, 0, None, cfg)

            multi = runtime.process_count() > 1 or cfg.elastic
            while True:
                try:
                    answered = tier.run(
                        health_fn=health_fn if multi else None,
                        shutdown=shutdown)
                    break
                except elastic.WorldChangedError as e:
                    grow = bool(getattr(e, "grow", False))
                    reconfigures += 1
                    if reconfigures > cfg.max_reconfigures:
                        raise faults.PeerFailureError(
                            f"world changed {reconfigures} times, over "
                            f"the --max-reconfigures "
                            f"{cfg.max_reconfigures} cap; exiting with "
                            "the last failure") from e
                    # Same release discipline as run_train's loop: the
                    # old replica's closure (engine/state on the dead
                    # generation's backend) and the exception chain's
                    # frames must be droppable before the reconfigure
                    # parks the old world.
                    infer = None
                    tier.set_infer(None)
                    exc = e
                    while exc is not None:
                        exc.__traceback__ = None
                        exc = exc.__cause__ or exc.__context__
                # Reconfigure OUTSIDE the except block (sys.exc_info
                # pins the traceback until the block exits).  The HTTP
                # listener stays up through the whole window: requests
                # keep admitting into the bounded queue and are
                # answered by the rebuilt replica.
                with goodput.get().timed("elastic_reconfigure"):
                    _elastic_reconfigure(cfg, tel, None, grow,
                                         purpose="serve")
                    # rebuild what is actually served — a hot-swapped
                    # replica must not silently revert on reconfigure
                    infer = _serve_build_replica(
                        cfg.replace(checkpoint_file=current_ckpt[0]),
                        model_name, dataset, buckets, sample_shape,
                        sample_dtype)
                    tier.set_infer(infer)
                logging.info(
                    f"serve: replica rebuilt for generation "
                    f"{elastic.generation()}; resuming with "
                    f"{tier.batcher.depth()} queued requests")
        logging.info(f"serve: stopped after answering {answered} "
                     f"requests")
        return {"answered": answered, "port": port,
                "model_name": model_name}
    finally:
        if tier is not None:
            tier.close()
        tracing.get().close()
        flightrec.get().close(
            "crash" if sys.exc_info()[0] is not None else "run_end")
        goodput.stop_exporter()
        goodput.get().close()
        tel.close()
        runtime.reset_compilation_cache()


def main(argv=None) -> int:
    cfg = config_from_argv(argv)
    if cfg.action == "lint":
        # Static analysis (analysis/ graftlint): pure AST work, no JAX
        # backend, no training banners.  Exit 0 = clean, 1 = findings.
        from .analysis.core import run_cli as lint_cli

        return lint_cli(json_output=cfg.lint_json,
                        paths=cfg.lint_paths or None,
                        changed_only=cfg.lint_changed_only,
                        base=cfg.lint_base or None)
    if cfg.action == "timeline":
        # Offline merge of per-rank JSONL + flight records into a Chrome
        # trace-event file (Perfetto-loadable) — no JAX backend touched.
        from . import timeline

        try:
            print(timeline.run_cli(cfg.rsl_path, out=cfg.timeline_out))
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
        return 0
    if cfg.action == "telemetry":
        # Offline aggregation of RSL_PATH/telemetry/rank*.jsonl — no
        # training banners, no JAX backend touched.
        try:
            print(telemetry.json_report(cfg.rsl_path)
                  if cfg.report_json else telemetry.report(cfg.rsl_path))
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
        return 0
    if cfg.action == "goodput":
        # Offline wall-clock attribution summary from the per-rank
        # goodput ledgers (RSL_PATH/goodput*.json).
        try:
            print(goodput.report(cfg.rsl_path))
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
        return 0
    if cfg.action == "roofline":
        # Offline per-op roofline attribution of a profiler trace — no
        # JAX backend touched (the analysis reads trace JSON + the HLO
        # text costs.json saved at compile time).
        from . import roofline

        try:
            print(roofline.run_cli(
                cfg.rsl_path, trace_dir=cfg.roofline_trace_dir,
                from_anomaly=cfg.roofline_from_anomaly,
                top=cfg.roofline_top, as_json=cfg.report_json))
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
        return 0
    if cfg.action == "fleet":
        # The standalone fleet collector (fleet.py): scrape every rank
        # exporter, merge, re-export, evaluate SLOs — a monitoring
        # process, never a member of the world, no JAX backend touched.
        from . import fleet

        return fleet.run_cli(cfg)
    if cfg.action == "frontdoor":
        # The fleet front door (serving/frontdoor.py): one client port
        # over many replicas — health-aware routing, SLO-driven
        # autoscale, canary rollout.  A control-plane process, never a
        # member of the world, no JAX backend touched.
        from .serving import frontdoor

        return frontdoor.run_cli(cfg)
    if cfg.action == "incidents":
        # Offline digest of the incident bundles a fleet run wrote.
        from . import slo

        print(slo.incidents_report(cfg.rsl_path))
        return 0
    if cfg.action == "sim":
        # Deterministic fleet simulator (sim/): replay a scenario
        # against the real control-plane policies under a virtual
        # clock — no JAX backend, no sockets, no wall clock.
        from .sim import runner as sim_runner

        try:
            return sim_runner.run_cli(cfg)
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
    if cfg.action == "bench-trend":
        # Regression ledger over the checked-in BENCH history; the
        # verdict gates CI (exit 1 on a fresh-vs-fresh regression).
        from . import benchtrend

        try:
            verdict, text = benchtrend.run_cli(
                bench_dir=cfg.trend_dir,
                threshold=cfg.trend_threshold,
                as_json=cfg.report_json)
        except ValueError as e:
            logging.error(f"{e}, exiting...")
            return 1
        print(text)
        return 0 if verdict else 1
    print("========================= start =========================")
    rc = 0
    try:
        if cfg.action == "train":
            run_train(cfg)
        elif cfg.action == "serve":
            run_serve(cfg)
        else:
            run_test(cfg)
    except ValueError as e:  # ref style: log and exit (classif.py:119,130)
        logging.error(f"{e}, exiting...")
        rc = 1
    except (faults.FatalFaultError, faults.PeerFailureError,
            faults.HealthTimeoutError) as e:
        # Agreed-upon fatal exit: every rank takes this path together
        # (see _health_boundary), so the nonzero status is coordinated
        # rather than one rank dying and the rest hanging.
        logging.error(f"fatal failure: {e}, exiting...")
        rc = 1
    if rc == 0:
        print("========================= end ==========================")
    if elastic.reconfigured():
        # A reconfigured process must not run interpreter teardown: the
        # parked pre-shrink coordinator service fatals when the GC
        # finally destroys it (elastic.py module doc).  Flush and leave.
        elastic.quiesce_exit(rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
