"""L4: training/eval engine (TPU-native replacement for ref classif.py)."""

from .engine import Engine, TrainState

__all__ = ["Engine", "TrainState"]
