"""The SPMD train/eval engine — the heart of the framework.

Replaces the reference's DDP machinery (ref classif.py:28-71 processData,
:122-138 optimizer + DistributedDataParallel wrap).  Where DDP hijacks
``loss.backward()`` to bucket-allreduce gradients over NCCL, here the whole
step — on-device augmentation, forward, loss, backward, gradient reduction,
optimizer update — is ONE jit-compiled XLA program over the device mesh:
the batch is sharded along the 'data' axis, params/optimizer state are
replicated, and XLA inserts the gradient all-reduce over ICI automatically
(the computation is expressed on *global* arrays; the collective appears
exactly where DDP's hidden allreduce was, but fused and overlapped by the
compiler).  tests/test_distributed.py proves the semantics: the sharded
step's gradients equal a single-device big-batch step's.

Design choices with reference citations:
  * aux-logit models (inception): loss = loss1 + 0.4*loss2
    (ref classif.py:49-53);
  * optimizers: Adam(lr=1e-3) | SGD(lr=1e-3, momentum=0.9) with per-epoch
    StepLR(gamma=0.1) for SGD only (ref classif.py:122-131) — expressed as
    an optax exponential_decay schedule with staircase per epoch;
  * feature_extract freezes the backbone via optax.multi_transform +
    set_to_zero over the structural head/backbone mask
    (ref utils.py:107-110, config.py:48);
  * metrics are *globally* reduced inside the step (fixes SURVEY defect #9:
    the reference reports rank-local, never-reduced loss/accuracy);
  * per-batch metric scalars stay on device; the driver syncs at most a few
    times per epoch (the reference's per-batch ``.item()`` at
    classif.py:61-62 forces a device sync every step).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import runtime
from ..data import augment
from ..models.registry import (AUX_LOGIT_MODELS, DROPOUT_MODELS,
                               REMAT_BLOCK_MODELS, trainable_mask)
from ..ops import per_example_correct
from ..ops.losses import LossFn
from ..precision import (LossScaleState, PrecisionPolicy, all_finite,
                         cast_floating, from_flags, tree_select)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    # Dynamic loss-scale state (precision.LossScaleState) — None for every
    # preset except f16, so bf16/f32 checkpoints and pytrees are unchanged.
    loss_scale: Any = None


def make_optimizer(optimizer: str, learning_rate: float, momentum: float,
                   lr_step_gamma: float, steps_per_epoch: int,
                   feature_extract: bool) -> optax.GradientTransformation:
    """Optimizer dispatch (ref classif.py:122-131)."""
    if optimizer == "adam":
        base = optax.adam(learning_rate)  # torch Adam defaults match optax
    elif optimizer == "SGD":
        # StepLR(step_size=1, gamma) per epoch == staircase exponential decay
        # every steps_per_epoch steps (ref classif.py:128,168-169).
        schedule = optax.exponential_decay(
            init_value=learning_rate,
            transition_steps=max(1, steps_per_epoch),
            decay_rate=lr_step_gamma,
            staircase=True)
        base = optax.sgd(schedule, momentum=momentum)
    else:
        raise ValueError(f"Invalid optimizer {optimizer!r}")
    if feature_extract:
        return optax.multi_transform(
            {"head": base, "backbone": optax.set_to_zero()},
            trainable_mask)
    return base


class Engine:
    """Builds and owns the jitted SPMD steps for one (model, config) pair."""

    def __init__(self, model, model_name: str, loss_fn: LossFn,
                 tx: optax.GradientTransformation, mean: float, std: float,
                 input_size: int, half_precision: bool = True,
                 grad_accum: int = 1,
                 precision: Optional[PrecisionPolicy] = None,
                 remat: str = "none"):
        self.model = model
        self.model_name = model_name
        self.loss_fn = loss_fn
        self.tx = tx
        self.mean = float(mean)
        self.std = float(std)
        self.input_size = int(input_size)
        # Explicit policy wins; the legacy bool maps onto the preset that
        # reproduces its historical behavior (True -> "bf16", False ->
        # "f32") so programmatic Engine(half_precision=...) callers keep
        # working unchanged.
        self.precision = precision or from_flags(None, half_precision)
        self.compute_dtype = self.precision.compute_dtype
        self.accum_dtype = self.precision.accum_dtype
        self.has_aux = model_name in AUX_LOGIT_MODELS
        self.uses_dropout = model_name in DROPOUT_MODELS
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        if remat not in ("none", "blocks", "full"):
            raise ValueError(f"remat must be none|blocks|full, got {remat!r}")
        self.remat = remat
        # Rematerialization of the grad-path forward.  Models with block
        # submodules (REMAT_BLOCK_MODELS) carry nn.remat at their block
        # boundaries (wired by the registry), which is both finer-grained
        # and param-tree-preserving; for flat models "blocks" falls back to
        # checkpointing the whole apply while SAVING matmul outputs (the
        # recompute is then the cheap elementwise work only).  "full" saves
        # nothing: maximum memory relief, backward recomputes the matmuls.
        model_handles_remat = (remat == "blocks"
                               and model_name in REMAT_BLOCK_MODELS)
        if remat == "full":
            self._grad_apply = jax.checkpoint(
                self._apply, static_argnums=(3,))
        elif remat == "blocks" and not model_handles_remat:
            self._grad_apply = jax.checkpoint(
                self._apply, static_argnums=(3,),
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        else:
            self._grad_apply = self._apply
        # State donation is dropped where the persistent compilation
        # cache would corrupt it (CPU cache-hit executables lose their
        # aliasing metadata — see runtime.donation_safe).
        donate = (0,) if runtime.donation_safe() else ()
        self.train_step = jax.jit(self._train_step, donate_argnums=donate)
        self.eval_step = jax.jit(self._eval_step)
        # The serving tier's program (cli.run_serve): AOT-lowered per
        # batch-size bucket so request-path shapes never compile.
        self.predict_step = jax.jit(self._predict_step)
        # Two-dispatch diagnostic variant of train_step: backward and
        # optimizer as SEPARATE compiled programs.  scripts/precision_gate.py
        # pins fused == unfused bit-identically in f32 — the proof that
        # fusing the optimizer into the step program (and thereby deleting
        # the optimizer_metrics_us dispatch) changed scheduling, not math.
        self._fwd_bwd_jit = jax.jit(self._fwd_bwd)
        self._opt_apply_jit = jax.jit(self._opt_apply,
                                      donate_argnums=donate)
        # Device-resident whole-epoch programs (see train_epoch/eval_epoch):
        # one XLA dispatch per epoch instead of one per step.
        self.train_epoch = jax.jit(self._train_epoch, donate_argnums=donate)
        self.eval_epoch = jax.jit(self._eval_epoch)
        self.train_epochs = jax.jit(self._train_epochs,
                                    donate_argnums=donate)

    # -- state ------------------------------------------------------------

    def init_state(self, key: jax.Array) -> TrainState:
        # All zoo models see 3-channel input regardless of source channels:
        # the augment pipeline repeats grayscale to 3ch (ref dataloader.py
        # TensorRepeat, :31-44), so the init dummy is always (.., .., 3).
        x = jnp.zeros((2, self.input_size, self.input_size, 3),
                      self.compute_dtype)
        variables = jax.jit(
            functools.partial(self.model.init, train=True)
        )({"params": key, "dropout": jax.random.fold_in(key, 1)}, x)
        # Master params live in param_dtype.  Flax initializes f32 (its
        # param_dtype default), so this cast is the identity for every
        # preset except bf16_full, where it halves param + optimizer-state
        # memory at the documented precision cost.
        params = cast_floating(variables["params"],
                               self.precision.param_dtype)
        try:  # abstract trace, no device work — gates _pregather
            from ..ops import flops as flops_mod
            self._flops_per_sample = flops_mod.train_flops_per_sample(
                self.model, params, variables.get("batch_stats", {}),
                batch=8, input_size=self.input_size)
        except Exception:
            # the analytic FLOPs count is optional (MFU gauge +
            # _pregather sizing only): any abstract-tracing failure for
            # an exotic model disables those, never the training run
            self._flops_per_sample = None
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.tx.init(params),
            loss_scale=(LossScaleState.create(self.precision.loss_scale)
                        if self.precision.scales_loss else None),
        )

    # -- shared pieces ----------------------------------------------------

    def _apply(self, params, batch_stats, imgs, train: bool,
               dropout_key: Optional[jax.Array]):
        """Returns (out, new_batch_stats, aux_loss): ``aux_loss`` is the
        sum of everything the model sowed into the 'losses' collection
        in train mode (e.g. the MoE load-balancing loss,
        models/moe.py) — 0.0 for models that sow nothing."""
        variables = {"params": params}
        has_bn = len(jax.tree_util.tree_leaves(batch_stats)) > 0
        if has_bn:
            variables["batch_stats"] = batch_stats
        rngs = ({"dropout": dropout_key}
                if (train and self.uses_dropout) else None)
        if train:
            out, updated = self.model.apply(
                variables, imgs, train=True, rngs=rngs,
                mutable=["batch_stats", "losses"])
            aux = sum(
                (jnp.sum(leaf) for leaf in
                 jax.tree_util.tree_leaves(updated.get("losses", {}))),
                jnp.zeros((), self.accum_dtype))
            # BN running stats are cross-step accumulators: policy demands
            # accum_dtype (flax already keeps them f32 — the EMA inside
            # _compute_stats promotes half inputs — so this is a guard,
            # not a conversion).
            new_bs = cast_floating(updated.get("batch_stats", batch_stats),
                                   self.accum_dtype)
            return out, new_bs, aux
        out = self.model.apply(variables, imgs, train=train, rngs=rngs)
        return out, batch_stats, jnp.zeros((), self.accum_dtype)

    def _reduce_loss(self, logits, labels, vmask):
        numer, denom = self.loss_fn(logits, labels)
        return (jnp.sum(numer * vmask)
                / jnp.maximum(jnp.sum(denom * vmask), 1e-9))

    # -- steps ------------------------------------------------------------

    def _train_step(self, state: TrainState, images_u8, labels, valid,
                    key: jax.Array
                    ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        step_key = jax.random.fold_in(key, state.step)
        aug_key, dropout_key = jax.random.split(step_key)
        return self._train_step_keys(state, images_u8, labels, valid,
                                     aug_key, dropout_key)

    def _train_step_keys(self, state: TrainState, images_u8, labels, valid,
                         aug_key, dropout_key
                         ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """Step body with the per-step keys already derived.  The epoch
        scans hoist key derivation (fold_in + split are ~40 serialized
        scalar-unit hash rounds — measurable per step on TPU) into ONE
        batched threefry before the loop; values are identical."""
        imgs = augment.train_transform(
            aug_key, images_u8, self.mean, self.std, self.input_size,
            out_dtype=self.compute_dtype)
        vmask = valid.astype(self.accum_dtype)

        if self.grad_accum > 1:
            return self._train_step_accum(state, imgs, labels, vmask,
                                          dropout_key)

        grads, new_bs, loss, correct = self._grads_and_metrics(
            state, imgs, labels, vmask, dropout_key)
        return self._finish_step(state, grads, new_bs, loss, correct, vmask)

    def _grads_and_metrics(self, state: TrainState, imgs, labels, vmask,
                           dropout_key):
        """Forward + backward of one full batch: (grads, new_bs, loss,
        correct).  Under dynamic loss scaling the *differentiated* output
        is loss * scale; gradients are unscaled before returning, and the
        reported loss is the unscaled one."""
        scale = (None if state.loss_scale is None
                 else state.loss_scale.scale)

        def compute_loss(params):
            out, new_bs, sown = self._grad_apply(params, state.batch_stats,
                                                 imgs, True, dropout_key)
            if self.has_aux:
                logits, aux_logits = out
                loss = (self._reduce_loss(logits, labels, vmask)
                        + 0.4 * self._reduce_loss(aux_logits, labels, vmask))
            else:
                logits = out
                loss = self._reduce_loss(logits, labels, vmask)
            loss = loss + sown
            scaled = loss if scale is None else loss * scale
            return scaled, (logits, new_bs, loss)

        (_, (logits, new_bs, loss)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(state.params)
        if scale is not None:
            grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
        correct = jnp.sum(per_example_correct(logits, labels) * vmask)
        return grads, new_bs, loss, correct

    def _finish_step(self, state: TrainState, grads, new_bs, loss, correct,
                     vmask) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """Shared optimizer-update + metrics tail of both step variants.

        Lives INSIDE the jitted train-step program (fused step): cast-grads
        -> optax update -> apply-updates -> metrics compile into the same
        executable as forward/backward, so there is no separate optimizer
        dispatch (the ``optimizer_metrics_us`` stage of PROFILE_BREAKDOWN
        collapses to zero extra dispatches).
        """
        # cast-grads: the optimizer and master-param update run in
        # param_dtype regardless of what dtype the backward produced.
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), grads, state.params)
        updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                                state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_ls = state.loss_scale
        if state.loss_scale is not None:
            # Overflow-skip: a non-finite gradient keeps params/opt
            # state/BN stats and halves the scale — all as jnp.where
            # selects, so the step remains ONE compiled program.  step
            # still advances (the _epoch_keys hoisting contract requires
            # +1 per iteration unconditionally).
            finite = all_finite(grads)
            new_params = tree_select(finite, new_params, state.params)
            new_opt_state = tree_select(finite, new_opt_state,
                                        state.opt_state)
            new_bs = tree_select(finite, new_bs, state.batch_stats)
            new_ls = state.loss_scale.adjust(
                finite, self.precision.loss_scale_growth)
        metrics = {
            "loss": loss,
            "correct": correct,
            "valid": jnp.sum(vmask),
        }
        return state.replace(step=state.step + 1, params=new_params,
                             batch_stats=new_bs,
                             opt_state=new_opt_state,
                             loss_scale=new_ls), metrics

    # -- unfused diagnostic path ------------------------------------------

    def _fwd_bwd(self, state: TrainState, images_u8, labels, valid,
                 key: jax.Array):
        if self.grad_accum > 1:
            raise ValueError("the unfused diagnostic path supports "
                             "grad_accum=1 only")
        step_key = jax.random.fold_in(key, state.step)
        aug_key, dropout_key = jax.random.split(step_key)
        imgs = augment.train_transform(
            aug_key, images_u8, self.mean, self.std, self.input_size,
            out_dtype=self.compute_dtype)
        vmask = valid.astype(self.accum_dtype)
        grads, new_bs, loss, correct = self._grads_and_metrics(
            state, imgs, labels, vmask, dropout_key)
        return grads, new_bs, loss, correct, vmask

    def _opt_apply(self, state: TrainState, grads, new_bs, loss, correct,
                   vmask):
        return self._finish_step(state, grads, new_bs, loss, correct, vmask)

    def train_step_unfused(self, state: TrainState, images_u8, labels,
                           valid, key: jax.Array
                           ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """The pre-fusion execution shape: backward and optimizer as TWO
        separately compiled dispatches.  Kept only so the precision gate
        can pin fused == unfused bit-identically in f32; production paths
        all use the fused ``train_step``/epoch programs."""
        grads, new_bs, loss, correct, vmask = self._fwd_bwd_jit(
            state, images_u8, labels, valid, key)
        return self._opt_apply_jit(state, grads, new_bs, loss, correct,
                                   vmask)

    def _train_step_accum(self, state: TrainState, imgs, labels, vmask,
                          dropout_key
                          ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """Gradient accumulation over K microbatches (ABSENT in the
        reference — SURVEY §2 parallelism checklist; framework addition).

        Exactness: the single-step loss is N(p)/D with D (the valid-mask /
        class-weight denominator) independent of params, so
        grad = (1/D) * grad(N).  Each microbatch contributes grad(N_k);
        the sum is scaled by the TOTAL denominator once at the end —
        matching the unaccumulated step to float tolerance, not just
        approximately (proven in tests/test_grad_accum.py).  Activation
        memory drops to one microbatch's worth.

        Documented divergences under K>1: BatchNorm stats are computed per
        microbatch (chained EMA) and dropout draws per microbatch — the
        same semantics every major framework's accumulation has.  Note
        also that microbatches are STRIDE-k row slices (rows j, j+k, ...;
        see ``shard`` below), not contiguous blocks: which rows share a
        microbatch therefore differs from a contiguous split, so chained
        BN EMAs and per-row dropout pairings differ from any
        contiguous-split implementation (gradients remain exact either
        way — the accumulation identity is order-independent).
        """
        k = self.grad_accum
        b = imgs.shape[0]
        if b % k:
            raise ValueError(
                f"global batch {b} not divisible by grad_accum={k}")
        mb = b // k

        def shard(x):
            # Stride-k microbatches (rows j, j+k, j+2k, ...), NOT
            # contiguous blocks: with the global batch sharded over
            # 'data' in per-device blocks whose size the per-replica
            # batch (and hence, given batch % k == 0, a multiple of k),
            # a stride-k slice takes exactly rows-per-device/k rows from
            # EVERY device's block — each scan iteration stays device-
            # local, no resharding collective.  A contiguous split would
            # make microbatch j span a fraction of every device only when
            # k <= world; for k > 1 generally it concentrates rows on few
            # devices and GSPMD inserts a reshard per iteration.
            return jnp.moveaxis(x.reshape((mb, k) + x.shape[1:]), 1, 0)

        imgs_m, labels_m, vmask_m = shard(imgs), shard(labels), shard(vmask)

        scale = (None if state.loss_scale is None
                 else state.loss_scale.scale)

        def numer_fn(params, batch_stats, im, lb, vm, dkey):
            out, new_bs, sown = self._grad_apply(params, batch_stats, im,
                                                 True, dkey)
            if self.has_aux:
                logits, aux_logits = out
                n_main, d = self.loss_fn(logits, lb)
                n_aux, _ = self.loss_fn(aux_logits, lb)
                numer = jnp.sum(n_main * vm) + 0.4 * jnp.sum(n_aux * vm)
            else:
                logits = out
                n_main, d = self.loss_fn(logits, lb)
                numer = jnp.sum(n_main * vm)
            # sown aux losses (e.g. MoE load balance) are computed per
            # MICROBATCH; weighting by this microbatch's denominator
            # makes the accumulated loss the denominator-weighted mean
            # of the per-microbatch aux values (documented divergence
            # from the K=1 step, which computes aux on the full batch).
            numer = numer + sown * jnp.sum(d * vm)
            correct = jnp.sum(per_example_correct(logits, lb) * vm)
            if scale is not None:
                numer = numer * scale
            return numer, (new_bs, jnp.sum(d * vm), correct)

        grad_fn = jax.value_and_grad(numer_fn, has_aux=True)

        def micro(carry, xs):
            grads_acc, numer, denom, correct, bs = carry
            i, im, lb, vm = xs
            # distinct dropout draw per microbatch (dropout models only)
            (n, (new_bs, d, c)), g = grad_fn(
                state.params, bs, im, lb, vm,
                jax.random.fold_in(dropout_key, i))
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, g)
            return (grads_acc, numer + n, denom + d, correct + c,
                    new_bs), None

        # Gradient accumulation happens in accum_dtype (f32 in every
        # shipped preset): bf16/f16 per-microbatch grads are promoted on
        # add, so the K-way sum never loses mantissa to the compute dtype.
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, self.accum_dtype), state.params)
        acc0 = jnp.zeros((), self.accum_dtype)
        (grads_n, numer, denom, correct, new_bs), _ = jax.lax.scan(
            micro, (zeros, acc0, acc0, acc0, state.batch_stats),
            (jnp.arange(k), imgs_m, labels_m, vmask_m))

        denom_safe = jnp.maximum(denom, 1e-9)
        # Under loss scaling the accumulated numerator (and hence grads_n)
        # carries the scale; fold the unscale into the single final divide.
        eff = denom_safe if scale is None else denom_safe * scale
        grads = jax.tree_util.tree_map(lambda g: g / eff, grads_n)
        loss = numer / eff
        return self._finish_step(state, grads, new_bs, loss, correct, vmask)

    # -- whole-epoch device-resident programs ----------------------------
    #
    # Small corpora (MNIST is 42 MB raw) live entirely in HBM, so the
    # per-step host round-trip — the reference's DataLoader handing batches
    # to the GPU every step (ref classif.py:41-44) — is pure overhead.
    # These lax.scan programs run a full epoch per XLA dispatch: per-step
    # index gather, augmentation, fwd/bwd, gradient all-reduce and update
    # all stay on device.  The per-step math is _train_step/_eval_step's,
    # so streaming and resident modes train identically
    # (tests/test_resident.py proves it).

    # Per-step in-scan gathers of 64 u8 rows cost 18.5 us/step on a v5e
    # (measured, scripts/trace_ops.py: row-granular HBM gathers don't
    # stream).  ONE bulk take of the whole epoch plan before the scan
    # removes two gather ops from the loop body: -30 us/step on the
    # cnn/b64 headline and a 1.27x win on the mlp, whose 80-us steps are
    # gather-bound.  It LOSES ~5% on compute-heavy steps (vit: 1.55 ms
    # steps hide the in-scan gather behind compute, while the bulk copy
    # is serialized ahead of the scan), so it is gated on the model's
    # analytic FLOPs/sample (computed abstractly in init_state) and on a
    # bytes cap for the epoch-plan copy.  Values are identical either
    # way — only the schedule moves.
    PREGATHER_MAX_BYTES = 1 << 30
    PREGATHER_MAX_FLOPS_PER_SAMPLE = 2e8

    _flops_per_sample: Optional[float] = None

    def _pregather(self, images_all, labels_all, idx):
        """(S, B) plan -> ((S, B, ...) images, (S, B) labels) or None."""
        if (self._flops_per_sample is None
                or self._flops_per_sample
                > self.PREGATHER_MAX_FLOPS_PER_SAMPLE):
            return None
        sample_bytes = (int(np.prod(images_all.shape[1:]))
                        * images_all.dtype.itemsize)
        if idx.size * sample_bytes > self.PREGATHER_MAX_BYTES:
            return None
        return (jnp.take(images_all, idx, axis=0),
                jnp.take(labels_all, idx, axis=0))

    def _epoch_keys(self, state: TrainState, key: jax.Array, n: int):
        """(aug_keys, dropout_keys), each (n, 2) u32 — the same values
        _train_step would derive per step, batched into one threefry.

        Correctness contract: assumes the scan body (_train_step_keys via
        _finish_step) advances state.step by EXACTLY 1 per iteration, so
        hoisted key i == fold_in(key, state.step + i) matches what the
        streaming path derives at that step.  tests/test_engine.py::
        test_epoch_keys_match_streaming_derivation pins this key-level
        equality so a future change to the step increment fails loudly.
        """
        step_keys = jax.vmap(
            lambda i: jax.random.fold_in(key, state.step + i)
        )(jnp.arange(n, dtype=jnp.int32))
        pairs = jax.vmap(jax.random.split)(step_keys)  # (n, 2, key)
        return pairs[:, 0], pairs[:, 1]

    def _train_epoch(self, state: TrainState, images_all, labels_all,
                     idx, valid, key: jax.Array
                     ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """idx/valid: (steps, global_batch) — the sampler's epoch plan."""
        aug_keys, dropout_keys = self._epoch_keys(state, key, idx.shape[0])
        pre = self._pregather(images_all, labels_all, idx)

        def pack(step_out):
            # ONE stacked ys leaf per step instead of three scalar leaves:
            # each scan output leaf costs a dynamic-update-slice per
            # iteration in the loop body.
            st, m = step_out
            return st, jnp.stack([m["loss"], m["correct"], m["valid"]])

        if pre is not None:
            def body(st, xs):
                im, lb, v, ak, dk = xs
                return pack(self._train_step_keys(st, im, lb, v, ak, dk))

            state, packed = jax.lax.scan(
                body, state, (*pre, valid, aug_keys, dropout_keys))
        else:
            def body(st, xs):
                ids, v, ak, dk = xs
                return pack(self._train_step_keys(
                    st, jnp.take(images_all, ids, axis=0),
                    jnp.take(labels_all, ids, axis=0), v, ak, dk))

            state, packed = jax.lax.scan(
                body, state, (idx, valid, aug_keys, dropout_keys))
        return state, {"loss": packed[:, 0], "correct": packed[:, 1],
                       "valid": packed[:, 2]}

    def _eval_epoch(self, state: TrainState, images_all, labels_all,
                    idx, valid) -> Dict[str, jax.Array]:
        zeros = {k: jnp.zeros((), self.accum_dtype)
                 for k in ("loss_numer", "loss_denom", "correct", "valid")}
        pre = self._pregather(images_all, labels_all, idx)
        if pre is not None:
            def body(carry, xs):
                im, lb, v = xs
                m = self._eval_step(state, im, lb, v)
                return jax.tree_util.tree_map(jnp.add, carry, m), None

            totals, _ = jax.lax.scan(body, zeros, (*pre, valid))
            return totals

        def body(carry, xs):
            ids, v = xs
            m = self._eval_step(state, jnp.take(images_all, ids, axis=0),
                                jnp.take(labels_all, ids, axis=0), v)
            return jax.tree_util.tree_map(jnp.add, carry, m), None

        totals, _ = jax.lax.scan(body, zeros, (idx, valid))
        return totals

    def _train_epochs(self, state: TrainState, images_all, labels_all,
                      idx_tr, valid_tr, vimages_all, vlabels_all,
                      idx_va, valid_va, keys
                      ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """K (train pass + validation pass) epochs in ONE dispatch.

        idx_tr/valid_tr: (K, S, B); idx_va/valid_va: (K, Sv, B);
        keys: (K,) per-epoch PRNG keys.  Returns per-epoch train loss
        traces (K, S) plus per-epoch train/valid summary scalars — the same
        quantities the one-epoch-at-a-time driver path computes, so the
        per-epoch log lines are reproduced exactly.  Used by the
        --epochs-per-dispatch throughput knob; the trade-off (documented in
        README) is that only the chunk-final state exists on host, so
        rolling checkpoints are written per chunk, not per epoch.
        """

        def epoch_body(st, xs):
            itr, vtr, iva, vva, key = xs
            st, m = self._train_epoch(st, images_all, labels_all, itr, vtr,
                                      key)
            ev = self._eval_epoch(st, vimages_all, vlabels_all, iva, vva)
            out = {"train_loss": m["loss"],
                   "train_correct": jnp.sum(m["correct"]),
                   "train_valid": jnp.sum(m["valid"]),
                   "eval": ev}
            return st, out

        return jax.lax.scan(epoch_body, state,
                            (idx_tr, valid_tr, idx_va, valid_va, keys))

    def _eval_step(self, state: TrainState, images_u8, labels, valid
                   ) -> Dict[str, jax.Array]:
        imgs = augment.eval_transform(images_u8, self.mean, self.std,
                                      self.input_size,
                                      out_dtype=self.compute_dtype)
        vmask = valid.astype(self.accum_dtype)
        out, _, _ = self._apply(state.params, state.batch_stats, imgs,
                             False, None)
        logits = out[0] if isinstance(out, tuple) else out
        numer, denom = self.loss_fn(logits, labels)
        correct = per_example_correct(logits, labels) * vmask
        return {
            "loss_numer": jnp.sum(numer * vmask),
            "loss_denom": jnp.sum(denom * vmask),
            "correct": jnp.sum(correct),
            "valid": jnp.sum(vmask),
        }

    def _predict_step(self, state: TrainState, images_u8
                      ) -> Tuple[jax.Array, jax.Array]:
        """Serving-side inference: (labels, confidences) per row.

        Eval-mode apply (BatchNorm running stats, no dropout) makes
        every output row a function of its own input row only — which
        is what lets the micro-batcher pad short batches with zero rows
        and discard the padded outputs (pinned by tests/test_serve.py).
        Softmax runs in accum_dtype so the confidence is honest even
        under bf16 compute."""
        imgs = augment.eval_transform(images_u8, self.mean, self.std,
                                      self.input_size,
                                      out_dtype=self.compute_dtype)
        out, _, _ = self._apply(state.params, state.batch_stats, imgs,
                                False, None)
        logits = out[0] if isinstance(out, tuple) else out
        probs = jax.nn.softmax(logits.astype(self.accum_dtype), axis=-1)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                jnp.max(probs, axis=-1))
