"""Per-request tracing for the serving tier (ISSUE 16, tentpole 1).

The serving tier's aggregate surface (``serve/request_latency_ms``
p95s, shed counters) answers "is the tier healthy"; this module answers
the next question an incident asks: "WHICH request, and where did its
time go?"  Every valid ``/predict`` request gets a deterministic id —
``r<rank>-<seq>``, the rank's admission sequence number, no randomness
— returned to the client as an ``X-DPT-Request-Id`` header and threaded
through the micro-batcher, so the request accumulates a span chain:

  queue_wait   admit() -> the driver pops it from the queue
  batch_form   pop -> the padded batch is assembled, infer dispatching
  infer        the injected predict program (per-batch, shared by every
               request in the bucket)
  respond      infer done -> the 200/500 is written back to the socket

Shed (503) and timeout (504) requests get a terminal ``shed`` /
``timeout`` span instead, so badput is traceable per request, not just
counted.  Spans are measured as a CHAIN of ``perf_counter`` stamps —
each span starts where the previous ended — so by construction
``sum(spans) == total_s`` (admission to answer), and the sum of the
pre-respond spans reconciles against the ``serve/request_latency_ms``
histogram observation for the same request (same contract discipline
as the goodput ledger's >=99% wall reconciliation; pinned by
tests/test_tracing.py and the serve gate).

One JSON record per request is appended to
``RSL_PATH/trace-rank<N>.jsonl`` at terminal time (the handler thread
answering the client writes it — exactly-once, guarded by the tracer
lock).  Record schema:

  {"kind": "request", "id": "r0-000007", "rank": 0, "seq": 7,
   "ts": <wall at finish>, "mono": <monotonic at finish>,
   "ts_admit": <wall at admission>, "mono_admit": <monotonic>,
   "status": 200, "outcome": "answered",      # answered|shed|timeout|failed
   "bucket": 4,                               # answered/failed only
   "spans": {"queue_wait": s, "batch_form": s, "infer": s, "respond": s},
   "total_s": <sum of spans>, "latency_ms": <histogram observation>,
   "lineage": "<sha256[:12]>"}   # serving lineage id, when set — the
                                 # checkpoint version that answered
                                 # (set_lineage; updated per hot-swap)

Clock contract (telemetry.py): ``ts`` stamps are wall clock and never
subtracted; ``mono`` orders records; every duration is a perf_counter
difference.  ``main.py timeline`` renders the records as a per-request
track, ``main.py fleet`` mines them for the offending ids in SLO
incident bundles, and the disabled default (``Tracer(enabled=False)``)
keeps train/test paths at zero cost, same shape as telemetry.get().
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

#: span names in chain order — rendering and reconciliation walk this.
SPAN_ORDER = ("queue_wait", "batch_form", "infer", "respond", "shed",
              "timeout")

#: outcomes that did not answer a 200 — the badput set incident bundles
#: and gates mine for offending request ids.
BAD_OUTCOMES = ("failed", "shed", "timeout")

_ID_RE = re.compile(r"^r(\d+)-(\d+)$")


def request_id(rank: int, seq: int) -> str:
    """The deterministic request id: ``r<rank>-<seq>`` (no randomness —
    the id IS the rank + admission order)."""
    return "r%d-%06d" % (rank, seq)


def build_request_record(*, rank: int, seq: int, ts_admit: float,
                         mono_admit: float, status: int, outcome: str,
                         spans: Dict[str, float], ts: float, mono: float,
                         bucket: Optional[int] = None,
                         latency_ms: Optional[float] = None,
                         attrs: Optional[Dict[str, Any]] = None,
                         lineage: Optional[str] = None) -> Dict[str, Any]:
    """One trace record, schema-factory form (shared with the fleet
    simulator, which passes virtual clocks): the rounding rules and the
    ``total_s == sum(spans)`` chain invariant live HERE, once, so the
    simulated stream reconciles through :func:`reconcile` by the same
    construction the live stream does."""
    record: Dict[str, Any] = {
        "kind": "request", "id": request_id(rank, seq), "seq": int(seq),
        "rank": int(rank),
        "ts_admit": ts_admit, "mono_admit": mono_admit,
        "status": int(status), "outcome": outcome,
        "spans": {k: round(float(v), 6) for k, v in spans.items()},
        "total_s": round(sum(float(v) for v in spans.values()), 6),
        "ts": ts, "mono": mono,
    }
    if bucket is not None:
        record["bucket"] = int(bucket)
    if latency_ms is not None:
        record["latency_ms"] = round(float(latency_ms), 3)
    if attrs:
        record["attrs"] = attrs
    if lineage is not None:
        record["lineage"] = lineage
    return record


def encode_record(record: Dict[str, Any]) -> str:
    """Canonical serialization (sorted keys) — the byte-stable form
    both the live writer and the simulator append."""
    return json.dumps(record, sort_keys=True, default=float)


class RequestTrace:
    """One request's span chain.  The handler thread creates it and
    finishes it; the driver thread marks the dequeue/infer boundaries in
    between — the phases are sequenced by the batcher handoff and the
    request's done-event, so each stamp has a single writer."""

    __slots__ = ("id", "seq", "ts_admit", "mono_admit", "spans", "bucket",
                 "latency_ms", "_tracer", "_mark", "_finished")

    def __init__(self, tracer: "Tracer", seq: int):
        self.id = request_id(tracer.rank, seq)
        self.seq = seq
        self.ts_admit = time.time()
        self.mono_admit = time.monotonic()
        self.spans: Dict[str, float] = {}
        self.bucket: Optional[int] = None
        self.latency_ms: Optional[float] = None
        self._tracer = tracer
        self._mark = time.perf_counter()
        self._finished = False

    # -- span chain (each closes the span since the previous mark) -----

    def _close(self, name: str) -> None:
        now = time.perf_counter()
        self.spans[name] = self.spans.get(name, 0.0) + (now - self._mark)
        self._mark = now

    def mark_admitted(self) -> None:
        """The batcher accepted the request: restart the chain so
        ``queue_wait`` measures queue time, not parse time."""
        self.ts_admit = time.time()
        self.mono_admit = time.monotonic()
        self._mark = time.perf_counter()

    def mark_dequeued(self) -> None:
        self._close("queue_wait")

    def mark_infer_start(self, bucket: int) -> None:
        self.bucket = int(bucket)
        self._close("batch_form")

    def mark_infer_end(self) -> None:
        self._close("infer")

    def note_latency(self, latency_ms: float) -> None:
        """The driver's serve/request_latency_ms observation for this
        request — the value the span sum reconciles against."""
        self.latency_ms = round(float(latency_ms), 3)

    # -- terminal ------------------------------------------------------

    def finish(self, status: int, outcome: str, **attrs: Any) -> None:
        """Close the terminal span and write the record (exactly once —
        a 504'd request whose batch later completes must not write a
        second record)."""
        terminal = {"shed": "shed", "timeout": "timeout"}.get(outcome,
                                                              "respond")
        self._close(terminal)
        self._tracer._write(self, status=int(status), outcome=outcome,
                            attrs=attrs or None)


class Tracer:
    """Per-rank trace sink: id allocation + the JSONL writer.  Disabled
    instances allocate nothing and write nothing (``start()`` returns
    None), so the train/test paths stay at zero cost."""

    def __init__(self, enabled: bool = False, rsl_path: str = ".",
                 rank: int = 0):
        self.enabled = enabled
        self.rank = int(rank)
        self.path = os.path.join(rsl_path, f"trace-rank{self.rank}.jsonl")
        self.write_errors = 0
        self.lineage: Optional[str] = None
        self._seq = 0
        self._lock = threading.Lock()
        self._file = None
        self._sink_dead = False

    def set_lineage(self, sha256: Optional[str]) -> None:
        """The serving lineage id (the served checkpoint's sha256,
        ISSUE 19 satellite): stamped into every subsequent record so an
        incident can say WHICH model version answered each request —
        updated at startup and at every /admin/reload hot-swap."""
        self.lineage = str(sha256)[:12] if sha256 else None

    def start(self) -> Optional[RequestTrace]:
        """Allocate the next request id and its trace (None when
        disabled — callers guard with ``if trace is not None``)."""
        if not self.enabled:
            return None
        with self._lock:
            self._seq += 1
            return RequestTrace(self, self._seq)

    def _write(self, trace: RequestTrace, *, status: int, outcome: str,
               attrs: Optional[Dict[str, Any]]) -> None:
        # Paired stamps at terminal time — the clock contract's
        # stamp-only wall time plus the ordering clock.
        record = build_request_record(
            rank=self.rank, seq=trace.seq,
            ts_admit=trace.ts_admit, mono_admit=trace.mono_admit,
            status=status, outcome=outcome, spans=trace.spans,
            ts=time.time(), mono=time.monotonic(),
            bucket=trace.bucket, latency_ms=trace.latency_ms,
            attrs=attrs, lineage=self.lineage)
        with self._lock:
            if trace._finished:
                return  # the 504-then-late-complete race: first wins
            trace._finished = True
            if self._sink_dead:
                return
            try:
                if self._file is None:
                    os.makedirs(os.path.dirname(self.path) or ".",
                                exist_ok=True)
                    self._file = open(self.path, "a", encoding="utf-8")
                self._file.write(encode_record(record) + "\n")
                # Requests are orders of magnitude rarer than train
                # steps: flush per record so gates and the fleet
                # collector read complete records mid-run.
                self._file.flush()
            except OSError as e:
                # Observability must never take the tier down: count,
                # kill this sink, keep serving.
                self.write_errors += 1
                self._sink_dead = True
                logging.error(
                    f"tracing: cannot write {self.path!r} ({e}); "
                    f"disabling request traces for rank {self.rank} — "
                    f"serving continues")

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
        self.enabled = False


_active = Tracer(enabled=False)


def get() -> Tracer:
    """The process's active tracer (a disabled no-op by default)."""
    return _active


def configure(rsl_path: str, enabled: bool, rank: int = 0) -> Tracer:
    """Install the process's tracer (run_serve calls this once, after
    runtime init so the rank is the global process index)."""
    global _active
    _active.close()
    _active = Tracer(enabled=enabled, rsl_path=rsl_path, rank=rank)
    return _active


# -- offline readers (timeline, incidents, gates) ----------------------

def load_records(rsl_path: str) -> List[Dict[str, Any]]:
    """Every request record under ``rsl_path/trace-rank*.jsonl``, torn
    tails tolerated (a record interrupted mid-write parses as garbage
    and is skipped, same stance as telemetry.load_events)."""
    records: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(rsl_path,
                                              "trace-rank*.jsonl"))):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail mid-write
                    if isinstance(rec, dict) \
                            and rec.get("kind") == "request":
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("rank", 0), r.get("seq", 0)))
    return records


def span_sum_s(record: Dict[str, Any],
               through: Optional[str] = None) -> float:
    """Sum of a record's spans in chain order, optionally only through
    the named span (``through="infer"`` gives the portion the
    serve/request_latency_ms observation covers)."""
    spans = record.get("spans", {})
    total = 0.0
    for name in SPAN_ORDER:
        if name in spans:
            total += float(spans[name])
        if name == through:
            break
    return total


def reconcile(records: List[Dict[str, Any]],
              tolerance_ms: float = 50.0,
              rel_tolerance: float = 0.2) -> List[str]:
    """The trace contract check, shared by tests and gates: every
    record's span sum equals its total, and for answered requests the
    pre-respond span sum matches the latency the histogram observed
    (within ``max(tolerance_ms, rel_tolerance * latency)``).  Returns
    one actionable line per violation — empty means reconciled."""
    problems: List[str] = []
    for rec in records:
        rid = rec.get("id", "?")
        total = float(rec.get("total_s", 0.0))
        sum_all = span_sum_s(rec)
        if abs(sum_all - total) > 1e-3:
            problems.append(
                f"{rid}: span sum {sum_all:.6f}s != total_s "
                f"{total:.6f}s — the span chain is torn")
        if rec.get("outcome") != "answered" \
                or rec.get("latency_ms") is None:
            continue
        latency_ms = float(rec["latency_ms"])
        core_ms = span_sum_s(rec, through="infer") * 1000.0
        tol = max(tolerance_ms, rel_tolerance * latency_ms)
        if abs(core_ms - latency_ms) > tol:
            problems.append(
                f"{rid}: pre-respond span sum {core_ms:.1f}ms vs "
                f"serve/request_latency_ms observation "
                f"{latency_ms:.1f}ms — off by more than {tol:.1f}ms")
    return problems


def rank_of_id(request_id: str) -> Optional[int]:
    """The rank encoded in a request id (``r1-000007`` -> 1), or None
    for a malformed id — incident bundles use this to name the replica
    an offending request died on."""
    m = _ID_RE.match(request_id or "")
    return int(m.group(1)) if m else None
