"""The fleet metrics collector: one view over every rank's exporter.

Every observability surface before this PR was per-rank (telemetry
JSONL, the goodput ledger, the /metrics+/healthz exporter each rank
binds at metrics_port + rank).  ``main.py fleet`` runs this module as a
standalone process — no JAX, no membership in the world — that turns
those N scrape targets into ONE fleet-level surface:

  scrape    every cycle, GET /metrics + /healthz from every candidate
            port (base..base+ranks-1).  Elastic-aware by construction:
            a joiner's exporter answers and appears within one
            interval; a departed rank fails ``stale_after`` consecutive
            scrapes and ages OUT of the merged series — the fleet view
            never re-exports a dead rank's frozen counters as live.
  merge     counters and gauges sum across alive ranks (keys carry
            their Prometheus labels, so dpt_goodput_seconds_total
            merges per category); histograms merge SKETCH-wise — each
            exporter now publishes its log-bucket occupancy as
            cumulative ``_bucket{le=...}`` lines, this module
            reconstructs the per-rank sketches (telemetry.Histogram
            .from_parts) and folds them (Histogram.merge), which is
            exact, so the fleet p95 carries the same <=1% sketch error
            as a single rank's.
  persist   one JSONL record per cycle (fleet-metrics.jsonl): merged
            series + per-target counters/health from the SAME cycle.
  re-export /metrics (Prometheus text, ``dpt_up <alive-count>``) and
            /fleet (the full cycle record as JSON) on fleet_port — the
            surface the ROADMAP's front door and autoscaler will poll.
  alert     with --slo-spec, each cycle's sample window feeds the PURE
            evaluator (slo.py); an objective that transitions to
            firing writes one self-contained incident-*.json bundle:
            the triggering windows, per-rank healthz snapshots, the
            suspect ranks (whose bad counters moved in the window),
            and the offending request ids mined from the serving
            tier's trace records (tracing.py).

The collector holds no lifetime state beyond its sample deque: kill it
and restart it mid-run and the fleet series continue from the next
scrape (counters are cumulative at the source).
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import re
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from . import deadline as dl
from . import slo, telemetry, tracing

#: how many cycles of samples the SLO window can look back over, as a
#: multiple of the longest declared window (bounded memory, plural so a
#: baseline sample older than the window always exists).
_WINDOW_SLACK = 3.0

_SCRAPE_TIMEOUT_S = 2.0

_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)$")
_LE_RE = re.compile(r'le="([^"]+)"')


# -- Prometheus text parsing ------------------------------------------

def parse_metrics(text: str) -> Dict[str, Any]:
    """Parse one exporter's /metrics body back into mergeable state:

      {"counters": {key: value},   # key includes labels when present
       "gauges":   {key: value},
       "histograms": {name: {"count","sum","min","max","nonpos",
                             "buckets": {idx: n}}}}

    Histogram sketches are reconstructed from the ``_bucket{le=...}``
    lines goodput.render_metrics emits: le is the geometric upper
    boundary exp((idx+1)*log(1.02)), so idx = round(ln(le)/g) - 1 and
    the cumulative counts difference back to per-bucket occupancy
    exactly.  Summary ``{quantile=...}`` lines are deliberately
    ignored: quantiles don't merge, sketches do."""
    growth = telemetry.Histogram._GROWTH_LOG
    types: Dict[str, str] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    raw_buckets: Dict[str, List[Tuple[float, int]]] = {}

    def _hist(name: str) -> Dict[str, Any]:
        return hists.setdefault(name, {
            "count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
            "nonpos": 0, "buckets": {}})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        kind = types.get(name)
        if kind == "counter":
            counters[name + labels] = value
        elif kind == "gauge":
            gauges[name + labels] = value
        # kind == "summary" lines are the per-rank quantiles: skipped,
        # they don't merge.  The sketch lines (_count/_sum/_min/_max/
        # _bucket) carry no TYPE of their own: route by suffix back to
        # the summary they extend.
        if kind is None:
            for suffix in ("_count", "_sum", "_min", "_max", "_bucket"):
                if not name.endswith(suffix):
                    continue
                base = name[: -len(suffix)]
                if types.get(base) != "summary":
                    break
                h = _hist(base)
                if suffix == "_count":
                    h["count"] = int(value)
                elif suffix == "_sum":
                    h["sum"] = value
                elif suffix == "_min":
                    h["min"] = value
                elif suffix == "_max":
                    h["max"] = value
                else:
                    le = _LE_RE.search(labels)
                    if le:
                        raw_buckets.setdefault(base, []).append(
                            (math.inf if le.group(1) == "+Inf"
                             else float(le.group(1)), int(value)))
                break
    for base, pairs in raw_buckets.items():
        h = _hist(base)
        prev = 0
        for le, cum in sorted(pairs, key=lambda p: p[0]):
            n = cum - prev
            prev = cum
            if n <= 0:
                continue
            if le == 0.0:
                h["nonpos"] = n
            elif le != math.inf:
                idx = int(round(math.log(le) / growth)) - 1
                h["buckets"][idx] = n
            # +Inf adds nothing: cum there == count
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def merge_targets(parsed: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-rank parses into the fleet view.  Counters and gauges
    sum by key; sketches fold via Histogram.merge (exact).  dpt_up is
    excluded — aliveness is the COLLECTOR's verdict (who answered this
    cycle), not a sum of self-reports."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, telemetry.Histogram] = {}
    for p in parsed:
        for k, v in p.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + v
        for k, v in p.get("gauges", {}).items():
            if k == "dpt_up":
                continue
            gauges[k] = gauges.get(k, 0.0) + v
        for name, st in p.get("histograms", {}).items():
            h = telemetry.Histogram.from_parts(
                name, st.get("count", 0), st.get("sum", 0.0),
                st.get("min", 0.0), st.get("max", 0.0),
                st.get("buckets", {}), nonpos=st.get("nonpos", 0))
            if name in hists:
                hists[name].merge(h)
            else:
                hists[name] = h
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def _hist_state(h: telemetry.Histogram) -> Dict[str, Any]:
    """A sketch's JSON-serializable state (the slo.py sample schema)."""
    return {"count": h.count, "sum": h.sum,
            "min": h.min if h.count else 0.0,
            "max": h.max if h.count else 0.0,
            "nonpos": h._nonpos,
            "buckets": dict(h._buckets)}


# -- sample / incident schema factories --------------------------------
#
# The fleet-metrics.jsonl sample and the incident bundle are CONTRACTS
# shared by the live collector below and the fleet simulator
# (sim/artifacts.py), which synthesizes the same shapes from a virtual
# clock — `main.py fleet`/`incidents` and slo.evaluate consume both
# streams identically because both go through these builders.

def build_fleet_sample(*, ts: float, mono: float, cycle: int,
                       alive: List[int], merged: Dict[str, Any],
                       targets: Dict[str, Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """One scrape-cycle sample (sans verdicts, which the caller appends
    after slo.evaluate).  Clock contract: ts is a stamp (never
    subtracted); mono is the ordering time and the SLO evaluator's
    pure ``t``."""
    return {
        "kind": "fleet_sample", "ts": ts, "mono": mono,
        "t": mono, "cycle": int(cycle),
        "alive": list(alive),
        "counters": merged["counters"],
        "gauges": merged["gauges"],
        "histograms": {n: _hist_state(h)
                       for n, h in merged["histograms"].items()},
        "targets": targets,
    }


def encode_sample(sample: Dict[str, Any]) -> str:
    """Canonical JSONL serialization of one sample (sorted keys) —
    byte-stable, which is what makes same-seed simulator runs
    byte-identical."""
    return json.dumps(sample, sort_keys=True, default=float)


def build_incident(*, name: str, spec: Dict[str, Any],
                   verdict: Dict[str, Any], cycle: int, ts: float,
                   alive: List[int], suspect_ranks: List[int],
                   offending_requests: List[str],
                   healthz: Dict[str, Any]) -> Dict[str, Any]:
    """One incident bundle document."""
    return {
        "kind": "incident", "slo": name,
        "slo_kind": spec["kind"], "spec": spec,
        "cycle": int(cycle), "ts": ts,
        "windows": verdict["windows"],
        "alive": list(alive),
        "suspect_ranks": list(suspect_ranks),
        "offending_requests": list(offending_requests),
        "healthz": healthz,
    }


def incident_filename(seq: int, name: str) -> str:
    return "incident-%03d-%s.json" % (int(seq), name)


def write_incident_bundle(rsl_path: str, seq: int, name: str,
                          bundle: Dict[str, Any]) -> Optional[str]:
    """Persist one bundle; returns the path or None on an unwritable
    disk (observability never takes the control plane down)."""
    path = os.path.join(rsl_path, incident_filename(seq, name))
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bundle, f, sort_keys=True, default=float, indent=1)
    except OSError as e:
        logging.error(f"fleet: cannot write incident bundle "
                      f"{path!r}: {e}")
        return None
    return path


def render_fleet_metrics(merged: Dict[str, Any], alive: int) -> str:
    """The merged series as Prometheus text — same exposition shape as
    the per-rank exporter, with ``dpt_up`` = the alive-rank count."""
    growth = telemetry.Histogram._GROWTH_LOG
    lines: List[str] = []
    typed: set = set()

    def _type(base: str, kind: str) -> None:
        if base not in typed:
            typed.add(base)
            lines.append("# TYPE %s %s" % (base, kind))

    for key in sorted(merged["counters"]):
        _type(key.split("{", 1)[0], "counter")
        lines.append("%s %.17g" % (key, merged["counters"][key]))
    for key in sorted(merged["gauges"]):
        _type(key.split("{", 1)[0], "gauge")
        lines.append("%s %.17g" % (key, merged["gauges"][key]))
    for name in sorted(merged["histograms"]):
        h = merged["histograms"][name]
        _type(name, "summary")
        for q in (0.5, 0.95, 0.99):
            lines.append('%s{quantile="%g"} %.17g'
                         % (name, q, h.quantile(q)))
        lines.append("%s_count %d" % (name, h.count))
        lines.append("%s_sum %.17g" % (name, h.sum))
        if h.count:
            lines.append("%s_min %.17g" % (name, h.min))
            lines.append("%s_max %.17g" % (name, h.max))
            cum = h._nonpos
            if cum:
                lines.append('%s_bucket{le="0"} %d' % (name, cum))
            for idx in sorted(h._buckets):
                cum += h._buckets[idx]
                lines.append('%s_bucket{le="%.17g"} %d'
                             % (name, math.exp((idx + 1) * growth), cum))
            lines.append('%s_bucket{le="+Inf"} %d' % (name, h.count))
    lines.append("# TYPE dpt_up gauge")
    lines.append("dpt_up %d" % alive)
    return "\n".join(lines) + "\n"


# -- the collector -----------------------------------------------------

class _Target:
    """One candidate rank exporter and its scrape health."""

    __slots__ = ("rank", "port", "fails", "alive", "parsed", "health")

    def __init__(self, rank: int, port: int):
        self.rank = rank
        self.port = port
        self.fails = 0
        self.alive = False
        self.parsed: Optional[Dict[str, Any]] = None
        self.health: Optional[Dict[str, Any]] = None


class FleetCollector:
    """Scrape, merge, persist, re-export, alert.  One thread of its
    own (the re-export HTTP server); ``run()`` drives the scrape loop
    on the caller's thread."""

    def __init__(self, rsl_path: str, ranks: int, metrics_port: int,
                 host: str = "127.0.0.1", interval_s: float = 1.0,
                 stale_after: int = 3, port: int = 0,
                 slos: Optional[List[Dict[str, Any]]] = None,
                 max_cycles: int = 0):
        if ranks < 1:
            raise ValueError(f"fleet needs >= 1 candidate rank, "
                             f"got {ranks}")
        if interval_s <= 0:
            raise ValueError(f"scrape interval must be > 0, "
                             f"got {interval_s}")
        self.rsl_path = rsl_path
        self.host = host
        self.interval_s = float(interval_s)
        self.stale_after = max(1, int(stale_after))
        self.port = int(port)
        self.slos = list(slos or [])
        self.max_cycles = int(max_cycles)
        self.cycle = 0
        self.incidents_written = 0
        self._targets = [_Target(r, metrics_port + r)
                         for r in range(int(ranks))]
        window = max((float(w["seconds"]) for s in self.slos
                      for w in s["windows"]), default=60.0)
        keep = max(8, int(window * _WINDOW_SLACK / self.interval_s) + 2)
        self._samples: Deque[Dict[str, Any]] = collections.deque(
            maxlen=keep)
        self._firing: set = set()
        self._lock = threading.Lock()
        self._latest: Optional[Dict[str, Any]] = None  # /fleet body
        self._latest_prom = "# TYPE dpt_up gauge\ndpt_up 0\n"
        self._stop = threading.Event()
        self._server = None
        self._thread = None
        self._sink = None

    # -- scraping ------------------------------------------------------

    def _fetch(self, port: int, path: str,
               budget: Optional[dl.Deadline] = None) -> Optional[str]:
        # deadline.fetch: hard per-call timeout, bounded further by the
        # cycle budget — a wedged exporter costs at most its share of
        # one cycle, never a stall past --interval (ISSUE 19 satellite).
        return dl.fetch(f"http://{self.host}:{port}{path}",
                        _SCRAPE_TIMEOUT_S, deadline=budget)

    def scrape_once(self) -> Dict[str, Any]:
        """One full cycle: probe every candidate, age out the silent,
        merge the alive, persist the sample, evaluate SLOs.  The whole
        scrape pass shares one Deadline budget — max(interval, one
        scrape timeout) — so N wedged exporters degrade to failed
        scrapes (age-out pressure), not a cycle that overruns its
        period."""
        self.cycle += 1
        budget = dl.Deadline(max(self.interval_s, _SCRAPE_TIMEOUT_S))
        for t in self._targets:
            body = self._fetch(t.port, "/metrics", budget)
            if body is None:
                t.fails += 1
                if t.fails >= self.stale_after and t.alive:
                    logging.info(
                        f"fleet: rank {t.rank} (:{t.port}) aged out "
                        f"after {t.fails} failed scrapes")
                if t.fails >= self.stale_after:
                    t.alive = False
                    t.parsed = None
                    t.health = None
                continue
            t.fails = 0
            if not t.alive:
                logging.info(f"fleet: rank {t.rank} (:{t.port}) joined")
            t.alive = True
            t.parsed = parse_metrics(body)
            health = self._fetch(t.port, "/healthz", budget)
            try:
                t.health = json.loads(health) if health else None
            except ValueError:
                t.health = None
        alive = [t for t in self._targets if t.alive]
        merged = merge_targets([t.parsed for t in alive
                                if t.parsed is not None])
        sample = build_fleet_sample(
            ts=time.time(), mono=time.monotonic(), cycle=self.cycle,
            alive=[t.rank for t in alive], merged=merged,
            targets={str(t.rank): {
                "port": t.port,
                "counters": (t.parsed or {}).get("counters", {}),
                "health": t.health,
            } for t in alive})
        self._samples.append(sample)
        verdicts = (slo.evaluate(self.slos, list(self._samples))
                    if self.slos else [])
        sample["verdicts"] = verdicts
        self._alert(verdicts, sample)
        self._persist(sample)
        with self._lock:
            self._latest = sample
            self._latest_prom = render_fleet_metrics(merged, len(alive))
        return sample

    # -- alerting ------------------------------------------------------

    def _alert(self, verdicts: List[Dict[str, Any]],
               sample: Dict[str, Any]) -> None:
        """Edge-detect newly-firing objectives and write their incident
        bundles; a cleared objective re-arms."""
        for v in verdicts:
            name = v["name"]
            if not v["firing"]:
                if name in self._firing:
                    logging.info(f"fleet: slo {name!r} recovered at "
                                 f"cycle {self.cycle}")
                self._firing.discard(name)
                continue
            if name in self._firing:
                continue  # still burning: one bundle per episode
            self._firing.add(name)
            self._write_incident(name, v, sample)

    def _suspects(self, spec: Dict[str, Any],
                  verdict: Dict[str, Any]) -> List[int]:
        """Ranks whose own bad counter moved inside the widest window —
        the merged series says THAT something burned, the per-target
        history says WHERE."""
        if spec.get("kind") != "ratio":
            return sorted(int(r) for r in sample_targets(self._samples))
        seconds = max(float(w["seconds"]) for w in spec["windows"])
        samples = list(self._samples)
        base, latest = slo._window(samples, seconds)
        key = spec["bad"]
        out = []
        for rank, doc in latest.get("targets", {}).items():
            end = float(doc.get("counters", {}).get(key, 0.0))
            start = float(base.get("targets", {}).get(rank, {})
                          .get("counters", {}).get(key, 0.0))
            if end - start > 0:
                out.append(int(rank))
        return sorted(out)

    def _offenders(self, sample: Dict[str, Any],
                   verdict: Dict[str, Any]) -> List[str]:
        """Request ids whose trace records ended badly inside the
        triggering window (wall-clock mapped via the window samples'
        own stamps, padded one interval for flush skew)."""
        seconds = max(float(w["seconds"]) for w in verdict["windows"])
        base, latest = slo._window(list(self._samples), seconds)
        lo = float(base.get("ts", 0.0)) - self.interval_s
        hi = float(latest.get("ts", 0.0)) + self.interval_s
        ids = []
        for rec in tracing.load_records(self.rsl_path):
            if rec.get("outcome") not in tracing.BAD_OUTCOMES:
                continue
            ts = float(rec.get("ts", 0.0))
            if lo <= ts <= hi:
                ids.append(rec["id"])
        return ids

    def _write_incident(self, name: str, verdict: Dict[str, Any],
                        sample: Dict[str, Any]) -> None:
        spec = next(s for s in self.slos if s["name"] == name)
        self.incidents_written += 1
        bundle = build_incident(
            name=name, spec=spec, verdict=verdict, cycle=self.cycle,
            ts=sample["ts"], alive=sample["alive"],
            suspect_ranks=self._suspects(spec, verdict),
            offending_requests=self._offenders(sample, verdict),
            healthz={rank: doc.get("health")
                     for rank, doc in sample["targets"].items()})
        path = write_incident_bundle(self.rsl_path,
                                     self.incidents_written, name, bundle)
        if path is None:
            return
        logging.warning(
            f"fleet: INCIDENT — slo {name!r} firing at cycle "
            f"{self.cycle}: suspects {bundle['suspect_ranks']}, "
            f"{len(bundle['offending_requests'])} offending "
            f"request(s) -> {path}")

    # -- persistence ---------------------------------------------------

    def _persist(self, sample: Dict[str, Any]) -> None:
        try:
            if self._sink is None:
                os.makedirs(self.rsl_path, exist_ok=True)
                self._sink = open(
                    os.path.join(self.rsl_path, "fleet-metrics.jsonl"),
                    "a", encoding="utf-8")
            self._sink.write(encode_sample(sample) + "\n")
            self._sink.flush()
        except OSError as e:
            logging.error(f"fleet: cannot persist fleet-metrics.jsonl "
                          f"({e}); collection continues")
            self._sink = None

    # -- re-export -----------------------------------------------------

    def start(self) -> None:
        """Bind the fleet exporter (port 0 in config disables; port 0
        here binds an ephemeral port, resolved into self.port)."""
        import http.server

        coll = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.startswith("/metrics"):
                    with coll._lock:
                        body = coll._latest_prom.encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/fleet"):
                    with coll._lock:
                        doc = coll._latest
                    body = json.dumps(doc, sort_keys=True,
                                      default=float).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes would drown the collector log

        self._server = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), _Handler)
        self.port = self._server.server_address[1]
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="fleet-exporter", daemon=True)
        self._thread.start()
        logging.info(f"fleet: re-exporting /metrics + /fleet "
                     f"on :{self.port}")

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> int:
        """The scrape loop: cycle, sleep, repeat until max_cycles /
        stop() / ^C.  Returns cycles completed."""
        started = 0
        try:
            while not self._stop.is_set():
                self.scrape_once()
                started += 1
                if self.max_cycles and started >= self.max_cycles:
                    break
                if self._stop.wait(self.interval_s):
                    break
        except KeyboardInterrupt:
            pass
        return started

    def close(self) -> None:
        self._stop.set()
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
            self._thread.join(timeout=5.0)
        if self._sink is not None:
            self._sink.close()
            self._sink = None


def sample_targets(samples: "collections.deque") -> List[str]:
    """Ranks present in the latest sample (helper for suspect listing
    when an objective isn't a ratio and no counter names a culprit)."""
    if not samples:
        return []
    return sorted(samples[-1].get("targets", {}).keys())


# -- CLI entry (main.py fleet) ----------------------------------------

def run_cli(cfg) -> int:
    """Run the collector per Config; returns a process exit code."""
    try:
        slos = slo.load_spec(cfg.slo_spec) if cfg.slo_spec else []
    except ValueError as e:
        print(f"fleet: {e}")
        return 2
    coll = FleetCollector(
        rsl_path=cfg.rsl_path, ranks=cfg.fleet_ranks,
        metrics_port=cfg.metrics_port, interval_s=cfg.fleet_interval,
        stale_after=cfg.fleet_stale_after, port=cfg.fleet_port,
        slos=slos, max_cycles=cfg.fleet_max_cycles)
    coll.start()
    print(f"fleet: scraping {cfg.fleet_ranks} candidate exporter(s) "
          f"at :{cfg.metrics_port}+rank every {coll.interval_s}s; "
          f"re-export on :{coll.port}"
          + (f"; {len(slos)} SLO objective(s)" if slos else ""))
    try:
        cycles = coll.run()
    finally:
        coll.close()
    alive = coll._samples[-1]["alive"] if coll._samples else []
    print(f"fleet: stopped after {cycles} cycle(s); last view had "
          f"{len(alive)} alive rank(s); {coll.incidents_written} "
          f"incident(s) written")
    return 0
