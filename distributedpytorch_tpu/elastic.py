"""L1: elastic world manager — survive rank loss, resume shrunken.

The PR-3/PR-4 failure machinery AGREES on failure (agree_health) and
exits every rank at the same boundary.  That turns a hang into a clean
crash; the job is still dead.  This module (``--elastic``) turns the
same verdict into a reconfiguration: the surviving ranks tear down the
collective runtime, re-elect a coordinator among themselves, re-init
``jax.distributed`` as a smaller world, rebuild the mesh, and resume
from the newest lineage-verified checkpoint.  Rank loss costs the work
since the last checkpoint — not the job.

How teardown actually works on this jaxlib (validated empirically on
jaxlib 0.4.36, CPU+gloo; every choice below is load-bearing):

* ``jax.distributed.shutdown()`` is UNUSABLE on survivors: it runs the
  shutdown barrier, which can't complete with a dead peer, and the
  client's default error callback terminates the process from a C++
  thread (xla distributed_runtime_client: no Python except can catch
  it).  So the runtime client is created by hand with
  ``shutdown_on_destruction=False`` and is never shut down.
* The old client and (on the old coordinator) the old service are
  deliberately LEAKED into ``_parked``: destroying the service closes
  the socket that still-live gloo poll threads watch, which is an
  unoverridable fatal; the gloo KV-store closures hold client refs
  anyway.  A leaked generation costs a few buffers and two idle
  threads — a reconfigure is rare enough that this never matters.
* ``missed_heartbeat_callback`` is unusable (pybind std::bad_cast ->
  terminate), so instead the heartbeat tolerance is set astronomically
  high: a dead task is never DECLARED dead by the runtime service —
  death is discovered where it is survivable, in the gloo collective
  error (~ms) or the bounded health agreement (--health-timeout).
* Teardown ordering matters, twice over.  The old backend is destroyed
  BEFORE the rendezvous: destroying it closes this process's gloo
  sockets, and that close is the only wake-up signal a peer still
  blocked inside a collective on the dead world ever gets.  (Measured:
  in a 3-rank ring the dead rank's recv-neighbor errors in
  milliseconds, but the NEXT rank's recv is posted on the neighbor —
  a live process — and blocks indefinitely once the neighbor leaves
  for the rendezvous.  Run the teardown first and that rank unblocks
  in milliseconds too.)  Destruction is by refcount, so callers must
  drop everything that pins the old client first — exception
  tracebacks whose frames hold the old arrays, loader meshes/
  shardings, and jax's module-level ``_mesh_object_dict`` which caches
  Mesh objects by device tuple (cli.run_train + _clear_backend_caches
  handle all of these).  After the new generation's ``manual_init``
  the caches are cleared AGAIN so nothing rebuilt against the blank
  interregnum state survives.
* Coordinator loss is NOT survivable: the distributed KV store lives in
  the rank-0 service process and dies with it.  Survivors of a
  coordinator loss get a clean error, not a new world.  (A replicated
  store is the jaxlib's work, not ours; the README documents this.)

Rendezvous between survivors cannot use the old collectives (they are
what just failed), so it runs over the shared filesystem — the same
trust anchor checkpoints already depend on: each survivor writes a
claim file under ``<elastic-dir>/gen-<g>/``, waits a settle window for
peers' claims, and the lowest-old-rank claimant elects itself the new
coordinator, binds a fresh port, and publishes ``world.json`` (member
list + coordinator address).  Followers poll for it and join with
``process_id = index of their old rank in the sorted member list`` —
deterministic, no second agreement round needed.

One residual sharp edge: a parked old SERVICE object still fatals at
interpreter teardown when the GC finally destroys it (its own poll
thread sees its own socket close).  ``quiesce_exit`` dodges this: a
process that has reconfigured flushes stdio and leaves via
``os._exit`` after its run completes, skipping interpreter teardown.
The same asymmetry forces an EXIT ORDER across processes: a service
host's exit closes the service socket, which is an instant fatal for
every peer whose parked client still polls it — while a departing
client is only ever noticed through the (disabled) heartbeats.  So
``quiesce_exit`` is also a barrier: peers drop a done marker in the
final generation's directory and leave; a service host waits
(bounded) for all of its peers' markers before it exits.

Worlds grow, too.  A joining process (fresh capacity, or a departed
rank restarting) drops a join-claim under ``<elastic-dir>/joins/`` and
waits.  Survivors poll that directory at every health boundary: when
the admission policy (``--elastic-target``) says yes, they agree to
grow through the same one-allgather health agreement that reports
failure, then run the SAME park/rendezvous/re-init machinery as a
shrink — except the claim set is complete (nothing died) and the
published ``world.json`` carries a ``joiners`` list.  The coordinator
answers each admitted claim with an ``admit-<id>.json`` marker naming
the joiner's new rank and the new coordinator address; the joiner
connects with ``manual_init`` and enters the run loop as a normal
member, restoring the newest lineage-verified checkpoint.  Declined
claims (over a ``fixed:N`` target, or a batch that cannot reach
``--elastic-min-world``) get a ``decline-<id>.json`` marker so the
joiner exits loudly instead of waiting forever.  A grow costs the
survivors one reconfigure window — the same price as a shrink.
"""

from __future__ import annotations

import gc
import json
import logging
import os
import socket
import time
from typing import List, Optional

from . import faults, flightrec, goodput, telemetry

# Leaked prior-generation (client, service) handles — see module doc.
# Never cleared: clearing is exactly the crash we are avoiding.
_parked: List[tuple] = []

# Coordinator ports of every generation this process has joined.  The
# keep-set for _close_stale_collective_sockets: coordination channels
# (ours AND parked ones, which still heartbeat/poll) must never be cut.
_coordinator_ports: set = set()
_app_ports: set = set()


def register_app_ports(*ports: int) -> None:
    """Exempt application listener ports from the parked-generation
    socket sweep (``_close_stale_collective_sockets``).

    A serving replica keeps its HTTP listeners (predict port, metrics
    exporter) open straight through a reconfigure — that is the
    zero-downtime contract — but an accepted connection on those
    listeners is an ESTABLISHED ephemeral<->app-port socket, exactly
    the shape the sweep would otherwise cut: every in-flight proxied
    request would die mid-response at each park.  Registered once per
    process as soon as the ports are known (serve startup / join)."""
    _app_ports.update(int(p) for p in ports if p)

_generation = 0          # 0 = the original world (no reconfigure yet)
_reconfigured = False

# Exit-order barrier state, set by a successful reconfigure:
# {"dir": <final generation dir>, "me": <my old rank>,
#  "peers": [other members' old ranks]}.  See quiesce_exit.
_barrier: Optional[dict] = None

# How long a claimant waits after the LAST new claim before treating
# the claim set as settled.  Survivors do NOT discover a failure at the
# same moment: the dead rank's direct gloo neighbor errors in
# milliseconds, while a rank whose recv is posted on a still-live
# neighbor only unblocks when that neighbor tears its backend down on
# the way to the rendezvous (see the module doc) — so the residual
# skew is backend-teardown time, seconds at worst.  The settle window
# must dominate that skew; the exactly-one-loss fast path below (all
# old_world-1 ranks claimed) keeps the COMMON case prompt regardless.
SETTLE_S = 20.0
# How long a follower polls for world.json before giving up (coordinator
# candidate crashed during rendezvous / coordinator loss).
WORLD_WAIT_S = 60.0
# Overall cap on one rendezvous round (claims + settle + join).
RENDEZVOUS_DEADLINE_S = 120.0
# How long a coordination-service host waits in quiesce_exit for its
# peers' done markers before exiting anyway (a peer that crashed after
# the reconfigure will never write one).
QUIESCE_BARRIER_S = 60.0
# How long a joiner waits for an admit/decline marker after dropping
# its claim.  Survivors only scan claims at health boundaries (epoch
# ends), so this must dominate an epoch plus a reconfigure window.
# Default only — `--elastic-join-wait` overrides it per run (short
# epochs don't need 10 minutes; simulator scenarios need seconds).
JOIN_WAIT_S = 600.0


class WorldChangedError(RuntimeError):
    """Control-flow signal, not a failure: the collective world changed
    membership (a member was lost, or a joiner was admitted) and this
    (healthy, --elastic) rank should reconfigure and resume instead of
    exiting.  Raised by the health boundary, caught by the elastic
    retraining loop in cli.run_train.  ``grow`` distinguishes the two:
    a grow parks and re-rendezvouses exactly like a shrink, but the
    full old world is still alive and the claim set includes joiners."""

    def __init__(self, msg: str, grow: bool = False):
        super().__init__(msg)
        self.grow = grow


def generation() -> int:
    """0 before any reconfigure, then 1, 2, ... per shrink or grow."""
    return _generation


def reconfigured() -> bool:
    """True once this process has torn down and re-joined at least one
    reconfigured world (shrunken or grown), or joined one mid-run —
    drivers must then exit via ``quiesce_exit``."""
    return _reconfigured


def _hosts_runtime_service() -> bool:
    """Does THIS process host any coordination service — parked (it
    was a past generation's coordinator) or live (it is the current
    one)?  Such a process's exit closes the service socket under its
    peers' still-polling clients, which is fatal for them."""
    if any(svc is not None for _, svc in _parked):
        return True
    try:
        from jax._src import distributed as jdist

        return jdist.global_state.service is not None
    except Exception:  # broad: exit-path probe — any failure means "no"
        return False


def _exit_barrier() -> None:
    """Hold a coordination-service host back until its peers are gone.

    Exit order between survivors is asymmetric (module doc): a service
    host leaving aborts every peer whose parked client still polls
    that service, while a client leaving is never noticed.  Peers
    announce their exit with a ``done-<old rank>.json`` marker in the
    final generation's directory; a host waits — bounded by
    QUIESCE_BARRIER_S, since a peer that crashed post-reconfigure will
    never write one — for all of its peers' markers.  A host that
    never completed a reconfigure (failure path) has no membership to
    wait on and lingers blind for the same bound.
    """
    if _barrier is None:
        if _hosts_runtime_service():
            time.sleep(QUIESCE_BARRIER_S)
        return
    _write_json(os.path.join(_barrier["dir"],
                             f"done-{_barrier['me']}.json"),
                {"pid": os.getpid()})
    if not _hosts_runtime_service():
        return
    deadline = time.monotonic() + QUIESCE_BARRIER_S
    while time.monotonic() < deadline:
        if all(os.path.exists(os.path.join(_barrier["dir"],
                                           f"done-{peer}.json"))
               for peer in _barrier["peers"]):
            return
        time.sleep(0.2)
    logging.warning(
        "ELASTIC: exit barrier timed out waiting for peers "
        f"{_barrier['peers']} — exiting anyway")


def quiesce_exit(rc: int) -> None:
    """Exit without interpreter teardown (see module doc: a parked old
    coordinator service fatals when the GC destroys it at shutdown).
    Flushes stdio and the telemetry/flight-recorder sinks first, so a
    reconfigured run loses nothing observable by exiting this way.
    A coordination-service host additionally waits for its peers' done
    markers (see _exit_barrier) so its exit cannot abort them."""
    try:
        # Exporter first (a scrape must not observe the half-final
        # ledger), then the goodput final reconcile + write, then the
        # telemetry/flightrec sinks.
        goodput.stop_exporter()
        goodput.get().close()
        telemetry.get().close()
        flightrec.get().close("run_end")
    except Exception:  # broad: nothing may stop the exit path
        pass
    try:
        _exit_barrier()
    except Exception:  # broad: ditto — the barrier is best-effort
        pass
    try:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:  # broad: ditto — flushing is best-effort here
        pass
    logging.shutdown()
    os._exit(rc)


def is_peer_loss(err: Optional[BaseException]) -> bool:
    """Classify an exception as "a peer vanished mid-collective".

    The gloo CPU transport surfaces a dead peer as ``ValueError``
    (jaxlib wraps the absl UNKNOWN status) whose text names the failed
    collective — 'Gloo AllGather failed', 'Connection closed by peer',
    'Connection reset'.  TPU runs surface peer loss through the same
    strings via the distributed runtime, or through the bounded health
    agreement (HealthTimeoutError) when the peer died between
    collectives.  PeerFailureError counts too: a peer that REPORTED
    fatal at the boundary is gone by the time we reconfigure.
    """
    if err is None:
        return False
    if isinstance(err, (faults.HealthTimeoutError,
                        faults.PeerFailureError)):
        return True
    text = str(err)
    markers = ("Gloo ", "Connection closed by peer", "Connection reset",
               "Socket closed", "connection refused",
               "Broken pipe", "peer is unavailable")
    return any(m.lower() in text.lower() for m in markers)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def manual_init(coordinator_address: str, num_processes: int,
                process_id: int) -> None:
    """Stand up one collective-runtime generation by hand.

    Equivalent to ``jax.distributed.initialize`` except for the three
    survival-critical knobs it does not expose (see module doc):
    ``shutdown_on_destruction=False``, a heartbeat tolerance high
    enough that death is never declared by the runtime service, and
    coordinator service creation decoupled from process_id 0's client
    so a reconfigure can re-elect.  Writes jax's distributed global
    state exactly the way ``initialize`` would, so everything
    downstream (``xla_bridge.make_cpu_client``'s collectives wiring,
    ``jax.process_index()``) sees a normal distributed runtime.
    """
    import jax

    from jax._src import distributed as jdist
    from jax._src.lib import xla_extension as xe

    # Every generation's CPU client must be built with gloo cross-process
    # collectives — including the first multi-process generation of a
    # world that BOOTED solo (``--elastic`` with no coordinator sets no
    # distributed state at startup, so runtime.initialize_distributed
    # never ran its gloo branch) — or the next health allgather dies
    # with "Multiprocess computations aren't implemented on the CPU
    # backend".  Harmless on TPU (the option is CPU-specific).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older/newer jax without the option
        pass

    gs = jdist.global_state
    if process_id == 0:
        port = coordinator_address.rsplit(":", 1)[1]
        gs.service = xe.get_distributed_runtime_service(
            "[::]:" + port, num_processes,
            heartbeat_interval=10, max_missing_heartbeats=100000,
            shutdown_timeout=5)
    client = xe.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=60,
        shutdown_timeout=5, heartbeat_interval=10,
        max_missing_heartbeats=100000,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    gs.client = client
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator_address
    # Every generation's coordinator port joins the keep-set:
    # _close_stale_collective_sockets must never cut a coordination
    # channel — parked clients keep polling their (parked) services,
    # and a cut channel polls an error whose default handler
    # TERMINATES the process (xla distributed client.h).
    _coordinator_ports.add(int(coordinator_address.rsplit(":", 1)[1]))


def _close_stale_collective_sockets() -> None:
    """Close the parked generations' gloo pair sockets at the OS level.

    Why so low-level: the PJRT client object is unfreeable from Python
    on this jaxlib — the Client<->Device wrapper cycle lives in C++
    refs the cyclic GC cannot see — so its gloo sockets can never be
    closed by dropping references.  But a peer blocked inside a
    collective on the dead world unblocks ONLY when the socket its
    recv is posted on closes (measured: it otherwise stays blocked
    until this whole process exits).  So the sockets are closed by fd.

    Selection: ESTABLISHED TCP sockets whose ports are NOT a known
    coordinator port or registered application port
    (``register_app_ports`` — a serve replica's predict/metrics
    listeners carry live client traffic through the reconfigure) on
    either end.  Gloo pairs are ephemeral-to-ephemeral, while every
    coordination-service channel (gRPC) has a coordinator port on one
    end — cutting one of those would fire the parked client's fatal
    PollForError handler.  Gloo listeners are in LISTEN state, so they
    survive too (harmless either way).  The parked runtime never uses
    these fds again (that is what parking means), so the close is
    one-way traffic: peers see EOF, we lose nothing.
    """
    states = {}
    for table in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(table) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            parts = line.split()
            try:
                lport = int(parts[1].rsplit(":", 1)[1], 16)
                rport = int(parts[2].rsplit(":", 1)[1], 16)
                states[parts[9]] = (lport, rport, parts[3])
            except (IndexError, ValueError):
                continue
    closed = 0
    for fd in os.listdir("/proc/self/fd"):
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue
        if not target.startswith("socket:["):
            continue
        ent = states.get(target[len("socket:["):-1])
        if ent is None:
            continue
        lport, rport, state = ent
        if state != "01":  # ESTABLISHED only
            continue
        if lport in _coordinator_ports or rport in _coordinator_ports:
            continue
        if lport in _app_ports or rport in _app_ports:
            continue  # live HTTP traffic, not a parked gloo pair
        try:
            os.close(int(fd))
            closed += 1
        except OSError:
            continue
    logging.warning(f"ELASTIC: closed {closed} stale collective "
                    f"socket(s) of the parked generation(s)")


def _park_current_generation() -> None:
    """Leak the live client+service and blank jax's distributed global
    state so the next generation can be written in."""
    from jax._src import distributed as jdist

    gs = jdist.global_state
    _parked.append((gs.client, gs.service))
    gs.client = None
    gs.service = None


def _clear_backend_caches() -> None:
    """Invalidate everything that memoized the OLD world's shape.

    ``_clear_backends`` drops jax's reference to the backend built
    against the old global state; ``process_count``/``local_devices``
    are module-level lru_caches that ``_clear_backends`` does NOT clear
    and would otherwise keep answering with the old world size.
    ``_mesh_object_dict`` is jax's Mesh-object cache, keyed by device
    tuple — left alone it pins the old devices (and through them the
    old client + its gloo sockets) forever, defeating the
    teardown-before-rendezvous unblocking in ``reconfigure``.

    The pin hunt below was empirical (referrer-graph walk on this
    jax/jaxlib): ``_backends`` must be cleared IN PLACE because the
    ``jax.lib.xla_bridge`` compat shim aliases the dict OBJECT — the
    rebind inside ``_clear_backends`` strands the old client in the
    shim's copy; and plain ``functools.lru_cache``s on jax modules
    (e.g. ``jax._src.api._check_sharding``) hold Devices in their KEY
    tuples and are invisible to ``jax.clear_caches()``, which only
    knows jax's own cache registries.
    """
    import functools

    import jax
    from jax._src import mesh as jax_mesh
    from jax._src import xla_bridge

    xla_bridge._backends.clear()
    xla_bridge._clear_backends()
    for cached in ("process_count", "local_devices", "device_count",
                   "process_indices"):
        fn = getattr(xla_bridge, cached, None)
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    getattr(jax_mesh, "_mesh_object_dict", {}).clear()
    jax.clear_caches()
    for obj in gc.get_objects():
        if isinstance(obj, functools._lru_cache_wrapper):
            mod = getattr(getattr(obj, "__wrapped__", None),
                          "__module__", "") or ""
            if mod.startswith("jax"):
                try:
                    obj.cache_clear()
                except Exception:  # a dying cache must not stop teardown
                    pass


# -- filesystem rendezvous --------------------------------------------


def default_elastic_dir(rsl_path: str) -> str:
    """``--elastic-dir`` default: inside the run directory, which the
    checkpoint machinery already requires to be shared across hosts."""
    return os.path.join(rsl_path, "elastic")


def _gen_dir(elastic_dir: str, gen: int) -> str:
    return os.path.join(elastic_dir, f"gen-{gen}")


def _write_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _claimed_ranks(gen_dir: str) -> List[int]:
    try:
        names = os.listdir(gen_dir)
    except OSError:
        return []
    ranks = []
    for name in names:
        if name.startswith("rank-") and name.endswith(".json"):
            try:
                ranks.append(int(name[len("rank-"):-len(".json")]))
            except ValueError:
                continue
    return sorted(ranks)


# -- join claims + admission policy (the grow half) -------------------


class JoinDeclinedError(RuntimeError):
    """The coordinator answered this join claim with a decline marker
    (over a fixed target, or the batch could not reach the min-world
    floor).  The joiner exits loudly instead of waiting forever."""


def _joins_dir(elastic_dir: str) -> str:
    return os.path.join(elastic_dir, "joins")


def request_join(elastic_dir: str) -> str:
    """Drop this process's join claim and return its id.

    The claim is ``joins/join-<host>-<pid>.json`` — content-addressed
    by claimant identity, so a retried write is idempotent and a
    duplicate file left by a torn retry dedupes in ``pending_joins``.
    Runs under the process retry policy at fault site ``elastic.join``
    (torn/duplicate/failed claim writes are injectable and retried
    with deterministic backoff).
    """
    joins = _joins_dir(elastic_dir)
    os.makedirs(joins, exist_ok=True)
    host = socket.gethostname() or "host"
    jid = f"{host}-{os.getpid()}"
    path = os.path.join(joins, f"join-{jid}.json")

    def _claim():
        _write_json(path, {"id": jid, "host": host, "pid": os.getpid()})
        # Fired AFTER the write so a torn/rank_join fault can hit the
        # claim file itself; an ioerror after the (idempotent) write
        # still exercises the backoff-retry-rewrite path.
        faults.fire("elastic.join", path=path)

    faults.retry(_claim, "elastic.join", transient=(OSError,))
    logging.warning(f"ELASTIC: join claim {jid} dropped in {joins}")
    return jid


def pending_joins(elastic_dir: str) -> List[str]:
    """Join-claim ids not yet answered by an admit/decline marker.

    Duplicate claim files for one claimant (a retried write that left
    two files behind) dedupe by the id INSIDE the claim, not the
    filename.  A torn/unreadable claim is skipped loudly — the
    joiner's retry policy rewrites it, or the joiner times out."""
    joins = _joins_dir(elastic_dir)
    try:
        names = os.listdir(joins)
    except OSError:
        return []
    ids = set()
    for name in sorted(names):
        if not (name.startswith("join-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(joins, name)) as f:
                ids.add(str(json.load(f)["id"]))
        except (OSError, ValueError, KeyError):
            logging.warning(
                f"ELASTIC: skipping unreadable join claim {name} "
                "(torn write? the claimant's retry rewrites it)")
    return [jid for jid in sorted(ids)
            if not os.path.exists(os.path.join(joins,
                                               f"admit-{jid}.json"))
            and not os.path.exists(os.path.join(joins,
                                                f"decline-{jid}.json"))]


def evaluate_join_policy(live_world: int, join_ids: List[str],
                         target: str, min_world: int):
    """The autoscaling decision, as a pure function so every rank and
    every test computes the same verdict from the same inputs.

    ``target`` is ``capacity`` (admit every claim — scale to whatever
    shows up) or ``fixed:N`` (admit only up to a world of N).  A batch
    whose admission would still leave the world below ``min_world`` is
    declined whole: a reconfigure window is not worth paying for a
    world that stays under the floor.  Returns ``(admit, declined)``
    where ``declined`` is ``[(id, reason), ...]``; both orderings are
    deterministic (sorted ids), so coordinator-assigned new ranks are
    reproducible."""
    ids = sorted(join_ids)
    declined = []
    if target == "capacity":
        admit = ids
    elif target.startswith("fixed:"):
        try:
            cap = int(target[len("fixed:"):])
        except ValueError:
            raise ValueError(
                f"--elastic-target {target!r}: expected 'capacity' or "
                "'fixed:<N>'")
        if cap < 1:
            raise ValueError(f"--elastic-target {target!r}: N must be "
                             ">= 1")
        room = max(0, cap - live_world)
        admit = ids[:room]
        declined = [(jid, f"world already at fixed target {cap} "
                          f"(live {live_world})") for jid in ids[room:]]
    else:
        raise ValueError(
            f"--elastic-target {target!r}: expected 'capacity' or "
            "'fixed:<N>'")
    if admit and live_world + len(admit) < min_world:
        declined += [(jid, f"grown world {live_world + len(admit)} "
                           f"would stay below --elastic-min-world "
                           f"{min_world}") for jid in admit]
        admit = []
    return admit, declined


def scan_joins(elastic_dir: str, live_world: int, target: str,
               min_world: int):
    """Health-boundary poll: pending claims put through the admission
    policy.  Returns ``(admit, declined)`` like evaluate_join_policy."""
    return evaluate_join_policy(live_world, pending_joins(elastic_dir),
                                target, min_world)


def decline_joins(elastic_dir: str, declined, gen: int) -> None:
    """Answer declined claims with marker files (idempotent) so their
    claimants stop waiting.  Only the main rank / coordinator writes
    these — one authoritative verdict per claim."""
    joins = _joins_dir(elastic_dir)
    os.makedirs(joins, exist_ok=True)
    for jid, reason in declined:
        path = os.path.join(joins, f"decline-{jid}.json")
        if os.path.exists(path):
            continue
        _write_json(path, {"id": jid, "reason": reason,
                           "generation": gen})
        logging.warning(f"ELASTIC: declined join {jid}: {reason}")


def wait_for_admission(elastic_dir: str, jid: str,
                       timeout_s: Optional[float] = None) -> dict:
    """Joiner side: poll for the coordinator's verdict on my claim.
    Returns the admit doc (generation, new_rank, new_world,
    coordinator, members, joiners); raises JoinDeclinedError on a
    decline marker, TimeoutError when no verdict lands in time (no
    --elastic run reaching health boundaries on this dir, or the claim
    arrived after the run ended)."""
    joins = _joins_dir(elastic_dir)
    wait_s = JOIN_WAIT_S if timeout_s is None else timeout_s
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        for name, is_decline in ((f"admit-{jid}.json", False),
                                 (f"decline-{jid}.json", True)):
            path = os.path.join(joins, name)
            if not os.path.exists(path):
                continue
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue  # mid-replace read; retry
            if is_decline:
                raise JoinDeclinedError(
                    f"elastic join {jid} declined: "
                    f"{doc.get('reason', 'unspecified')}")
            return doc
        time.sleep(0.2)
    # Signal before raising: a joiner that gives up is a capacity event
    # the fleet operator needs in the JSONL, not just a stack trace on a
    # host that's about to be recycled.
    telemetry.get().event("elastic/join_wait_timeout", jid=jid,
                          wait_s=wait_s, elastic_dir=elastic_dir)
    raise TimeoutError(
        f"elastic join {jid}: no admit/decline marker within "
        f"{wait_s:.0f}s — is an --elastic run reaching health "
        f"boundaries on {elastic_dir}?")


def join_world(elastic_dir: str,
               timeout_s: Optional[float] = None) -> dict:
    """A joining process's whole entry: claim, wait for the verdict,
    connect to the published world.  Returns the same shape as
    ``reconfigure`` (generation/members/joiners/coordinator/new_rank/
    new_world).  The collective connect runs under the retry policy at
    fault site ``elastic.grow_reinit`` — the joiner can race the new
    coordinator's service coming up, exactly like a shrink follower.

    A joiner is never the coordinator (survivors elect among
    themselves; its new rank starts past the member list), so there is
    no service to host and nothing parked before the init — failures
    before a successful connect raise normally."""
    global _generation, _reconfigured, _barrier
    jid = request_join(elastic_dir)
    doc = wait_for_admission(elastic_dir, jid, timeout_s)
    gen = int(doc["generation"])
    new_rank = int(doc["new_rank"])
    new_world = int(doc["new_world"])
    logging.warning(
        f"ELASTIC: join {jid} admitted into generation {gen} as rank "
        f"{new_rank} of {new_world} (coordinator {doc['coordinator']})")

    def _reinit():
        faults.fire("elastic.grow_reinit")
        manual_init(doc["coordinator"], new_world, new_rank)

    faults.retry(_reinit, "elastic.grow_reinit",
                 transient=(OSError, TimeoutError, RuntimeError))
    # Drop anything jax memoized before the distributed init (a local
    # backend built during warm-up imports would otherwise shadow the
    # collective one).
    _clear_backend_caches()
    members = sorted(doc.get("members", []))
    joiners = list(doc.get("joiners", []))
    _barrier = {"dir": _gen_dir(elastic_dir, gen), "me": f"join-{jid}",
                "peers": [str(m) for m in members]
                + [f"join-{j}" for j in joiners if j != jid]}
    _generation = gen
    _reconfigured = True
    return {"generation": gen, "members": members, "joiners": joiners,
            "coordinator": doc["coordinator"], "new_rank": new_rank,
            "new_world": new_world}


def _rendezvous(elastic_dir: str, gen: int, old_rank: int,
                old_world: int, grow: bool = False,
                target: str = "capacity", min_world: int = 1) -> dict:
    """One claim/elect/publish round.  Returns the world.json doc:
    ``{"generation": g, "members": [old ranks...], "joiners": [ids...],
    "coordinator": addr}``.

    Every survivor: write my claim, wait for the claim set to settle
    (no new claim for SETTLE_S).  Lowest claimed old rank: self-elect,
    bind a free port, publish world.json.  Everyone else: poll for
    world.json, check membership.  A straggler that claims after the
    settle window missed the generation — it finds itself absent from
    ``members`` and fails loudly rather than wedging the new world.

    A GROW round differs in three ways: the full old world claims (so
    the every-rank-claimed refusal is suppressed), completion means
    all old ranks plus at least one pending join claim, and the
    coordinator re-runs the admission policy at publish time — its
    verdict is authoritative — publishing the admitted ids as
    ``joiners`` and answering each with an ``admit-<id>.json`` marker
    carrying the joiner's new rank, while declined claims get decline
    markers.  Joiner ranks are assigned past the member list in
    sorted-id order, so every rank derives the same world layout.
    """
    gen_dir = _gen_dir(elastic_dir, gen)
    os.makedirs(gen_dir, exist_ok=True)
    _write_json(os.path.join(gen_dir, f"rank-{old_rank}.json"),
                {"old_rank": old_rank, "pid": os.getpid()})
    world_path = os.path.join(gen_dir, "world.json")

    deadline = time.monotonic() + RENDEZVOUS_DEADLINE_S
    members = [old_rank]
    last_change = time.monotonic()
    while time.monotonic() < deadline:
        if os.path.exists(world_path):
            break  # someone already elected and published
        now_claimed = _claimed_ranks(gen_dir)
        if now_claimed != members:
            members = now_claimed
            last_change = time.monotonic()
        # Fast path for the common cases — exactly one rank lost, or a
        # grow where everyone is still alive: once every expected old
        # rank has claimed (and, growing, at least one join claim is
        # visible), there is no one left to wait for — publish
        # immediately instead of sitting out the settle window (which
        # exists to cover multi-loss, where the claim set can't tell
        # us when it is complete).
        complete = len(members) == (old_world if grow
                                    else old_world - 1)
        if grow:
            complete = complete and bool(pending_joins(elastic_dir))
        settled = complete \
            or (time.monotonic() - last_change) >= SETTLE_S
        # The settle window can only end the wait for the would-be
        # coordinator; followers keep polling for world.json so a
        # slow-to-settle coordinator doesn't strand them.
        if settled and members and members[0] == old_rank:
            if len(members) >= old_world and not grow:
                raise RuntimeError(
                    "elastic rendezvous: every rank of the old world "
                    f"claimed generation {gen} ({members}) — nothing "
                    "actually died; refusing to reconfigure")
            joiners: List[str] = []
            if grow:
                joiners, declined = evaluate_join_policy(
                    len(members), pending_joins(elastic_dir), target,
                    min_world)
                decline_joins(elastic_dir, declined, gen)
            host = os.environ.get("JAX_ELASTIC_HOST", "localhost")
            address = f"{host}:{_free_port()}"
            doc = {"generation": gen, "members": members,
                   "joiners": joiners, "coordinator": address}
            _write_json(world_path, doc)
            for i, jid in enumerate(joiners):
                _write_json(
                    os.path.join(_joins_dir(elastic_dir),
                                 f"admit-{jid}.json"),
                    {"id": jid, "generation": gen,
                     "new_rank": len(members) + i,
                     "new_world": len(members) + len(joiners),
                     "coordinator": address, "members": members,
                     "joiners": joiners})
            return doc
        time.sleep(0.2)

    waited = time.monotonic()
    while time.monotonic() - waited < WORLD_WAIT_S:
        if os.path.exists(world_path):
            try:
                with open(world_path) as f:
                    doc = json.load(f)
                if doc.get("generation") == gen:
                    if old_rank not in doc.get("members", []):
                        raise RuntimeError(
                            f"elastic rendezvous: rank {old_rank} "
                            f"missed generation {gen} (members "
                            f"{doc.get('members')}) — claimed after "
                            "the settle window; exiting rather than "
                            "wedging the new world")
                    return doc
            except (OSError, ValueError):
                pass  # mid-replace read; retry
        time.sleep(0.2)
    raise RuntimeError(
        f"elastic rendezvous: no world.json for generation {gen} "
        f"within {WORLD_WAIT_S}s — coordinator candidate lost?")


def reconfigure(elastic_dir: str, old_rank: int, old_world: int,
                grow: bool = False, target: str = "capacity",
                min_world: int = 1, purpose: str = "train") -> dict:
    """Tear down the current generation and join the reconfigured one —
    shrunken after a peer loss, or grown (``grow=True``) after the
    health boundary agreed to admit join claims.

    Returns ``{"generation", "members", "joiners", "coordinator",
    "new_rank", "new_world", "purpose"}``.  The collective-runtime
    re-init (the transient-failure-prone part: a follower can race the
    new coordinator's service coming up) runs under the process retry
    policy at fault site ``elastic.reinit`` (``elastic.grow_reinit``
    when growing).

    ``purpose`` tags what the world is FOR ("train" | "serve") in the
    logs and the returned info: a serving reconfigure answers requests
    throughout (the queue is host-side and survives), while a training
    reconfigure rewinds to the epoch boundary — the audit trail must
    distinguish them.
    """
    global _generation, _reconfigured, _barrier
    gen = _generation + 1
    logging.warning(
        f"ELASTIC: rank {old_rank} reconfiguring "
        f"({'grow' if grow else 'shrink'}, {purpose}) from world size "
        f"{old_world} (generation {gen})")
    # Tear the failed generation down BEFORE the rendezvous: closing
    # our gloo sockets is the wake-up signal for any peer still
    # blocked inside a collective on the dead world.  Done after the
    # rendezvous instead, that peer stays blocked through our whole
    # settle window and misses the generation.  The gc.collect frees
    # the old arrays' buffers; the socket close is separate because
    # the client object itself is unfreeable (see
    # _close_stale_collective_sockets).
    _park_current_generation()
    _barrier = None  # a failed round must not reuse stale membership
    try:
        _clear_backend_caches()
        gc.collect()
        _close_stale_collective_sockets()
        doc = _rendezvous(elastic_dir, gen, old_rank, old_world,
                          grow=grow, target=target, min_world=min_world)
        members = sorted(doc["members"])
        joiners = list(doc.get("joiners", []))
        new_rank = members.index(old_rank)
        new_world = len(members) + len(joiners)
        site = "elastic.grow_reinit" if grow else "elastic.reinit"

        def _reinit():
            faults.fire(site)
            manual_init(doc["coordinator"], new_world, new_rank)

        # RuntimeError covers a failed/timed-out connect to a
        # coordinator service that isn't up yet — same classification
        # as runtime.init.
        faults.retry(_reinit, site,
                     transient=(OSError, TimeoutError, RuntimeError))
        # Again, post-reinit: drop anything rebuilt against the blank
        # interregnum global state while the rendezvous was running.
        _clear_backend_caches()
    except BaseException:
        # Past the park there is no way back: this process can never
        # survive interpreter teardown again (the GC destroying a
        # parked service is the fatal this module exists to dodge),
        # so a failed reconfigure logs the full error and leaves
        # through quiesce_exit instead of raising.
        logging.error(
            f"ELASTIC: rank {old_rank} failed to join generation "
            f"{gen}; exiting", exc_info=True)
        quiesce_exit(1)

    # Barrier tokens: members keep their old-rank integer (marker
    # filenames unchanged from the shrink-only protocol); joiners are
    # addressed by claim id — join_world writes the matching token.
    _barrier = {"dir": _gen_dir(elastic_dir, gen), "me": old_rank,
                "peers": [m for m in members if m != old_rank]
                + [f"join-{j}" for j in joiners]}
    _generation = gen
    _reconfigured = True
    logging.warning(
        f"ELASTIC: generation {gen} up — old rank {old_rank} is now "
        f"rank {new_rank} of {new_world} "
        f"({len(joiners)} joined; coordinator {doc['coordinator']})")
    return {"generation": gen, "members": members, "joiners": joiners,
            "coordinator": doc["coordinator"], "new_rank": new_rank,
            "new_world": new_world, "purpose": purpose}


def _reset_for_tests() -> None:
    """Test hook: forget generations WITHOUT touching parked handles
    (parked objects must stay leaked even in tests)."""
    global _generation, _reconfigured, _barrier
    _generation = 0
    _reconfigured = False
    _barrier = None
