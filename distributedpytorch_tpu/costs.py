"""L2: one provenance-stamped cost registry for compiled programs.

MFU gauges, ``scripts/profile_breakdown.py`` and ad-hoc roofline math all
need "how many FLOPs does this program move per invocation" — and before
this module each consumer derived the number its own way (analytic jaxpr
counting here, XLA ``cost_analysis()`` there), so two reports could
silently disagree about the same executable.  This registry is the single
resting place:

* ``record(name, compiled)`` — pull FLOPs / bytes-accessed out of an AOT
  ``Compiled`` object's ``cost_analysis()`` (the XLA estimate for the
  exact HLO that will run).  The AOT warmup (cli._aot_warmup) records
  every program it compiles.
* ``record_analytic(name, ...)`` — register a hand/jaxpr-derived count
  (ops.flops) under the same roof, tagged ``source="analytic"`` so a
  reader can always tell which methodology produced a number.
* ``save(rsl_path)`` — persist the registry to ``RSL_PATH/costs.json``
  with run-level provenance (device kind, jax version, wall/mono stamps),
  where the telemetry report and profile_breakdown can load it instead of
  re-deriving.

Every ``record*`` also emits a ``cost_analysis`` telemetry event, so the
per-rank JSONL carries the numbers even if the process dies before
``save`` runs.  All entry points are advisory: a backend whose
``cost_analysis`` raises (some CPU builds) degrades to ``flops=None``
rather than failing the warmup.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

import jax

from . import telemetry

_lock = threading.Lock()
_registry: Dict[str, dict] = {}


def reset() -> None:
    """Drop all recorded entries (start of each run; tests)."""
    with _lock:
        _registry.clear()


def _device_kind() -> Optional[str]:
    try:
        devs = jax.devices()
        return devs[0].device_kind if devs else None
    except Exception:
        # provenance is best-effort: an uninitialized backend (unit
        # tests constructing entries off-device) records kind=None
        return None


def _first_analysis(compiled: Any) -> Optional[dict]:
    """``cost_analysis()`` returns a dict on current jax, a list of dicts
    on older versions, and raises on some backends — normalise to one
    dict or None."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        # cost_analysis is advisory and backend-dependent (raises
        # NotImplemented/Internal on some builds) — record None, never
        # fail the warmup that called us
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if isinstance(ca, dict) else None


def _stamp(entry: dict) -> dict:
    # Paired stamps, same contract as telemetry records.
    entry["ts"] = time.time()
    entry["mono"] = time.monotonic()
    entry["device_kind"] = _device_kind()
    entry["jax_version"] = jax.__version__
    return entry


def record(name: str, compiled: Any) -> dict:
    """Register an AOT-compiled executable's XLA cost estimate.

    ``flops``/``bytes_accessed`` are per *invocation* of the program (so
    an epoch-fused program reports the whole epoch's FLOPs, a step
    program one step's).  Missing metrics record as None — an explicit
    "the backend would not say", never a silent zero.
    """
    ca = _first_analysis(compiled)

    def _metric(key: str) -> Optional[float]:
        if ca is None or key not in ca:
            return None
        try:
            v = float(ca[key])
        except (TypeError, ValueError):
            return None
        return v if v >= 0 else None  # XLA uses negatives for "unknown"

    entry = _stamp({
        "source": "xla_cost_analysis",
        "flops": _metric("flops"),
        "bytes_accessed": _metric("bytes accessed"),
    })
    with _lock:
        _registry[name] = entry
    telemetry.get().event("cost_analysis", program=name,
                          source=entry["source"], flops=entry["flops"],
                          bytes_accessed=entry["bytes_accessed"])
    return entry


def record_analytic(name: str, *, flops: Optional[float] = None,
                    flops_per_sample: Optional[float] = None,
                    note: Optional[str] = None) -> dict:
    """Register an analytically-derived count (ops.flops / jaxpr walk)."""
    entry = _stamp({
        "source": "analytic",
        "flops": float(flops) if flops is not None else None,
        "flops_per_sample": (float(flops_per_sample)
                             if flops_per_sample is not None else None),
    })
    if note:
        entry["note"] = note
    with _lock:
        _registry[name] = entry
    telemetry.get().event("cost_analysis", program=name,
                          source=entry["source"], flops=entry["flops"],
                          flops_per_sample=entry.get("flops_per_sample"))
    return entry


def record_mfu_denominator(peak: float, dtype: str,
                           device_kind: Optional[str] = None) -> dict:
    """Register WHICH peak-FLOPs denominator this run's MFU numbers use.

    Honest-MFU bookkeeping (ops.flops per-dtype table): a bf16 run divides
    by the bf16 peak, an f32 run by the f32 peak.  costs.json and the
    telemetry stream both carry the record, so any MFU figure in bench/
    telemetry output can be traced back to its denominator."""
    entry = _stamp({
        "source": "peak_table",
        "peak_flops_per_chip": float(peak),
        "peak_dtype": str(dtype),
    })
    if device_kind:
        entry["device_kind"] = device_kind
    with _lock:
        _registry["mfu_denominator"] = entry
    telemetry.get().event("cost_analysis", program="mfu_denominator",
                          source=entry["source"],
                          peak_flops_per_chip=entry["peak_flops_per_chip"],
                          peak_dtype=entry["peak_dtype"])
    return entry


def registry() -> Dict[str, dict]:
    """Snapshot copy of the current registry (program name -> entry)."""
    with _lock:
        return {k: dict(v) for k, v in _registry.items()}


def save(rsl_path: str) -> Optional[str]:
    """Write ``RSL_PATH/costs.json``; returns the path (None if empty).

    One file per run directory — the caller gates on the main process so
    multi-host runs don't race on the write (every host compiles the
    same programs, so rank 0's numbers speak for all)."""
    progs = registry()
    if not progs:
        return None
    doc = {
        "device_kind": _device_kind(),
        "jax_version": jax.__version__,
        "saved_at": {"ts": time.time(), "mono": time.monotonic()},
        "programs": progs,
    }
    os.makedirs(rsl_path, exist_ok=True)
    path = os.path.join(rsl_path, "costs.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load(rsl_path: str) -> Optional[dict]:
    """Read a saved ``costs.json`` back (None if absent/unreadable)."""
    try:
        with open(os.path.join(rsl_path, "costs.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
