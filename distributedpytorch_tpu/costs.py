"""L2: one provenance-stamped cost registry for compiled programs.

MFU gauges, ``scripts/profile_breakdown.py`` and ad-hoc roofline math all
need "how many FLOPs does this program move per invocation" — and before
this module each consumer derived the number its own way (analytic jaxpr
counting here, XLA ``cost_analysis()`` there), so two reports could
silently disagree about the same executable.  This registry is the single
resting place:

* ``record(name, compiled)`` — pull FLOPs / bytes-accessed out of an AOT
  ``Compiled`` object's ``cost_analysis()`` (the XLA estimate for the
  exact HLO that will run).  The AOT warmup (cli._aot_warmup) records
  every program it compiles.
* ``record_analytic(name, ...)`` — register a hand/jaxpr-derived count
  (ops.flops) under the same roof, tagged ``source="analytic"`` so a
  reader can always tell which methodology produced a number.
* ``save(rsl_path)`` — persist the registry to ``RSL_PATH/costs.json``
  with run-level provenance (device kind, jax version, wall/mono stamps),
  where the telemetry report and profile_breakdown can load it instead of
  re-deriving.

Every ``record*`` also emits a ``cost_analysis`` telemetry event, so the
per-rank JSONL carries the numbers even if the process dies before
``save`` runs.  All entry points are advisory: a backend whose
``cost_analysis`` raises (some CPU builds) degrades to ``flops=None``
rather than failing the warmup.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from . import telemetry

_lock = threading.Lock()
_registry: Dict[str, dict] = {}

# Optimized-HLO text larger than this is not persisted into costs.json
# (a costs file is provenance, not an artifact dump); the per-op
# roofline join then degrades to name heuristics for that program.
_HLO_TEXT_CAP = 4 * 1024 * 1024


def reset() -> None:
    """Drop all recorded entries (start of each run; tests)."""
    with _lock:
        _registry.clear()


def _device_kind() -> Optional[str]:
    try:
        devs = jax.devices()
        return devs[0].device_kind if devs else None
    except Exception:
        # provenance is best-effort: an uninitialized backend (unit
        # tests constructing entries off-device) records kind=None
        return None


def _first_analysis(compiled: Any) -> Optional[dict]:
    """``cost_analysis()`` returns a dict on current jax, a list of dicts
    on older versions, and raises on some backends — normalise to one
    dict or None."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        # cost_analysis is advisory and backend-dependent (raises
        # NotImplemented/Internal on some builds) — record None, never
        # fail the warmup that called us
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if isinstance(ca, dict) else None


def _stamp(entry: dict) -> dict:
    # Paired stamps, same contract as telemetry records.
    entry["ts"] = time.time()
    entry["mono"] = time.monotonic()
    entry["device_kind"] = _device_kind()
    entry["jax_version"] = jax.__version__
    return entry


def record(name: str, compiled: Any, hlo: bool = False) -> dict:
    """Register an AOT-compiled executable's XLA cost estimate.

    ``flops``/``bytes_accessed`` are per *invocation* of the program (so
    an epoch-fused program reports the whole epoch's FLOPs, a step
    program one step's).  Missing metrics record as None — an explicit
    "the backend would not say", never a silent zero.

    With ``hlo=True`` the optimized HLO text (``compiled.as_text()``) is
    kept alongside, bounded by ``_HLO_TEXT_CAP``: it is what lets the
    roofline analyzer (roofline.py) join a profiler trace's per-op
    events against analytic per-op FLOPs/bytes (``hlo_op_costs``)
    when the trace itself carries no cost metadata.
    """
    ca = _first_analysis(compiled)

    def _metric(key: str) -> Optional[float]:
        if ca is None or key not in ca:
            return None
        try:
            v = float(ca[key])
        except (TypeError, ValueError):
            return None
        return v if v >= 0 else None  # XLA uses negatives for "unknown"

    entry = _stamp({
        "source": "xla_cost_analysis",
        "flops": _metric("flops"),
        "bytes_accessed": _metric("bytes accessed"),
    })
    if hlo:
        try:
            text = compiled.as_text()
        except Exception:
            text = None  # HLO text is advisory, like cost_analysis
        if isinstance(text, str) and text:
            # The instruction count survives even when the text itself
            # is over the persistence cap: it is the compile-cost
            # proxy the scan-over-layers work sizes itself by
            # (O(depth) -> O(1) HLO), and it is a single int.
            entry["hlo_instructions"] = hlo_instruction_count(text)
            if len(text) <= _HLO_TEXT_CAP:
                entry["hlo"] = text
    with _lock:
        _registry[name] = entry
    telemetry.get().event("cost_analysis", program=name,
                          source=entry["source"], flops=entry["flops"],
                          bytes_accessed=entry["bytes_accessed"],
                          hlo_instructions=entry.get("hlo_instructions"))
    return entry


def record_analytic(name: str, *, flops: Optional[float] = None,
                    flops_per_sample: Optional[float] = None,
                    note: Optional[str] = None) -> dict:
    """Register an analytically-derived count (ops.flops / jaxpr walk)."""
    entry = _stamp({
        "source": "analytic",
        "flops": float(flops) if flops is not None else None,
        "flops_per_sample": (float(flops_per_sample)
                             if flops_per_sample is not None else None),
    })
    if note:
        entry["note"] = note
    with _lock:
        _registry[name] = entry
    telemetry.get().event("cost_analysis", program=name,
                          source=entry["source"], flops=entry["flops"],
                          flops_per_sample=entry.get("flops_per_sample"))
    return entry


def record_mfu_denominator(peak: float, dtype: str,
                           device_kind: Optional[str] = None) -> dict:
    """Register WHICH peak-FLOPs denominator this run's MFU numbers use.

    Honest-MFU bookkeeping (ops.flops per-dtype table): a bf16 run divides
    by the bf16 peak, an f32 run by the f32 peak.  costs.json and the
    telemetry stream both carry the record, so any MFU figure in bench/
    telemetry output can be traced back to its denominator."""
    entry = _stamp({
        "source": "peak_table",
        "peak_flops_per_chip": float(peak),
        "peak_dtype": str(dtype),
    })
    if device_kind:
        entry["device_kind"] = device_kind
    with _lock:
        _registry["mfu_denominator"] = entry
    telemetry.get().event("cost_analysis", program="mfu_denominator",
                          source=entry["source"],
                          peak_flops_per_chip=entry["peak_flops_per_chip"],
                          peak_dtype=entry["peak_dtype"])
    return entry


def registry() -> Dict[str, dict]:
    """Snapshot copy of the current registry (program name -> entry)."""
    with _lock:
        return {k: dict(v) for k, v in _registry.items()}


def save(rsl_path: str) -> Optional[str]:
    """Write ``RSL_PATH/costs.json``; returns the path (None if empty).

    One file per run directory — the caller gates on the main process so
    multi-host runs don't race on the write (every host compiles the
    same programs, so rank 0's numbers speak for all)."""
    progs = registry()
    if not progs:
        return None
    doc = {
        "device_kind": _device_kind(),
        "jax_version": jax.__version__,
        "saved_at": {"ts": time.time(), "mono": time.monotonic()},
        "programs": progs,
    }
    os.makedirs(rsl_path, exist_ok=True)
    path = os.path.join(rsl_path, "costs.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load(rsl_path: str) -> Optional[dict]:
    """Read a saved ``costs.json`` back (None if absent/unreadable)."""
    try:
        with open(os.path.join(rsl_path, "costs.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- per-op analytic costs from optimized HLO text ---------------------
#
# XLA's cost_analysis() speaks per PROGRAM; a profiler trace speaks per
# OP (instruction name in ``args.hlo_op``).  The bridge is the optimized
# HLO text: instruction names there are exactly the trace's op names
# (module-unique by XLA construction), and shapes + opcodes are enough
# for analytic FLOPs/bytes per execution of each instruction.  The
# counting conventions mirror ops/flops.py: matmul/conv at 2*MACs,
# elementwise at one FLOP per output element, reductions at one per
# input element, data movement at zero; bytes are the operand + result
# footprint of the instruction itself (a fusion's interior traffic stays
# on-chip, which is precisely what makes fusion a roofline win).

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

# One output-element FLOP each; everything arithmetic that XLA leaves
# unfused.  Transcendentals cost more microscopically but never matter
# at roofline granularity.
_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "negate", "abs", "sign",
    "maximum", "minimum", "power", "remainder", "exponential", "log",
    "log-plus-one", "exponential-minus-one", "tanh", "logistic", "sqrt",
    "rsqrt", "cbrt", "sine", "cosine", "tan", "atan2", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "compare", "select",
    "clamp", "and", "or", "xor", "not", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "convert",
    "is-finite", "erf",
}
_REDUCTIONS = {"reduce", "reduce-window", "select-and-scatter"}

_SHAPE_RE = re.compile(r"\b([a-z]+\d*(?:e\d+m\d+\w*)?)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([^\s=]+)\s*=\s*(\(?.*?\)?)\s+([a-z][\w-]*)\(")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([^\s(]+)\s*(?:\(.*)?\{\s*$")


def _shape_list(text: str) -> List[Tuple[str, int]]:
    """Every ``dtype[dims]`` in ``text`` as (dtype, element_count)."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dtype, n))
    return out


def _shapes_bytes(shapes: List[Tuple[str, int]]) -> float:
    return float(sum(n * _DTYPE_BYTES.get(dt, 4) for dt, n in shapes))


def _operand_span(line: str, opcode: str) -> str:
    """The operand list of an instruction line: the balanced paren group
    right after the opcode (attrs follow the closing paren)."""
    i = line.find(opcode + "(")
    if i < 0:
        return ""
    i += len(opcode)
    depth = 0
    for j in range(i, len(line)):
        if line[j] == "(":
            depth += 1
        elif line[j] == ")":
            depth -= 1
            if depth == 0:
                return line[i + 1:j]
    return line[i + 1:]


def _dims_of(shape_text: str) -> List[int]:
    m = _SHAPE_RE.search(shape_text)
    if not m:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def _dot_flops(line: str, result_elems: float, operands: str) -> float:
    """2 * output elements * contraction size, contraction dims read
    from the lhs_contracting_dims attribute against the lhs shape."""
    op_shapes = _SHAPE_RE.findall(operands)
    lhs_dims = []
    if op_shapes:
        lhs_dims = [int(d) for d in op_shapes[0][1].split(",") if d]
    m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", line)
    k = 1
    if m and lhs_dims:
        for idx in m.group(1).split(","):
            if idx and int(idx) < len(lhs_dims):
                k *= lhs_dims[int(idx)]
    elif lhs_dims:
        k = lhs_dims[-1]  # degraded: assume last-dim contraction
    return 2.0 * result_elems * k


def _conv_flops(line: str, result_elems: float, operands: str) -> float:
    """2 * output elements * kernel-spatial * kernel-input-features,
    kernel dim roles read from the dim_labels attribute."""
    op_shapes = _SHAPE_RE.findall(operands)
    if len(op_shapes) < 2:
        return 0.0
    kdims = [int(d) for d in op_shapes[1][1].split(",") if d]
    m = re.search(r"dim_labels=[^_\s,]+_([^-\s,]+)->", line)
    if m and len(m.group(1)) == len(kdims):
        spec = m.group(1)
        k = 1.0
        for pos, ch in enumerate(spec):
            if ch == "i" or ch.isdigit():
                k *= kdims[pos]
        return 2.0 * result_elems * k
    # Degraded: whole kernel divided by its (unknown-position) output
    # features — drop the largest dim as the best "o" guess.
    prod = 1.0
    for d in kdims:
        prod *= d
    return 2.0 * result_elems * prod / max(kdims, default=1)


def hlo_instruction_count(hlo_text: str) -> int:
    """Total instruction count across every computation of an optimized
    HLO module — the program-size metric behind the scan-over-layers
    win (an unrolled depth-L model carries ~L copies of each block
    instruction; under ``lax.scan`` one copy, so the count collapses
    from O(depth) to O(1)).  Counts every ``%name = shape opcode(...)``
    line, parameters included; relative comparisons (scan vs noscan of
    the same model) are what the number is for."""
    return sum(1 for line in hlo_text.splitlines()
               if _INSTR_RE.match(line))


def hlo_op_costs(hlo_text: str) -> Dict[str, dict]:
    """Analytic per-op {flops, bytes, opcode, dtype} from optimized HLO.

    Keys are instruction names exactly as a profiler trace's
    ``args.hlo_op`` reports them.  FLOPs/bytes are per single execution
    of the instruction (a trace event is one execution, so
    achieved-rate math multiplies by the observed event count).  Fusions
    sum the FLOPs of their called computation but count only their own
    operand/result bytes.  Anything unparseable degrades to an absent
    key, never an exception — the roofline join then classifies that op
    by name heuristic and says so.
    """
    comps: Dict[str, List[tuple]] = {}
    current: Optional[str] = None
    for raw in hlo_text.splitlines():
        line = raw.rstrip()
        if current is None:
            m = _COMP_RE.match(line.strip())
            if m and line.rstrip().endswith("{"):
                current = m.group(1)
                comps[current] = []
            continue
        if line.strip() == "}":
            current = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, result_text, opcode = m.groups()
        comps[current].append((name, opcode, result_text, line))

    def _instr_flops(opcode: str, result_text: str, line: str,
                     seen: frozenset) -> float:
        result_elems = float(sum(n for _, n in _shape_list(result_text)))
        operands = _operand_span(line, opcode)
        if opcode == "dot":
            return _dot_flops(line, result_elems, operands)
        if opcode == "convolution":
            return _conv_flops(line, result_elems, operands)
        if opcode == "fusion":
            m = re.search(r"calls=%([^\s,)]+)", line)
            if m:
                return _comp_flops(m.group(1), seen)
            return 0.0
        if opcode in _REDUCTIONS:
            shapes = _shape_list(operands)
            return float(shapes[0][1]) if shapes else 0.0
        if opcode in _ELEMENTWISE:
            return result_elems
        return 0.0

    def _comp_flops(comp: str, seen: frozenset) -> float:
        if comp in seen:  # malformed/recursive text: refuse the cycle
            return 0.0
        total = 0.0
        for _name, opcode, result_text, line in comps.get(comp, []):
            total += _instr_flops(opcode, result_text, line,
                                  seen | {comp})
        return total

    out: Dict[str, dict] = {}
    for comp, instrs in comps.items():
        for name, opcode, result_text, line in instrs:
            if opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element"):
                continue
            try:
                result_shapes = _shape_list(result_text)
                operands = _operand_span(line, opcode)
                flops = _instr_flops(opcode, result_text, line,
                                     frozenset())
                bytes_ = _shapes_bytes(result_shapes) \
                    + _shapes_bytes(_shape_list(operands))
                dtype = result_shapes[0][0] if result_shapes else None
            except (ValueError, IndexError):
                continue
            out[name] = {"opcode": opcode, "flops": flops,
                         "bytes": bytes_, "dtype": dtype}
    return out
