"""Goodput ledger + live Prometheus exporter.

Attributes 100% of the driver thread's wall clock to a closed set of
non-overlapping categories, so "where did the time go" is a run
artifact instead of a forensic exercise:

  ``compute``              fused-step / eval dispatch (device work the
                           driver is blocked on)
  ``compile``              AOT warmup + recompiles
  ``data_wait``            consumer-side input starvation (the streaming
                           loop's wait window — includes loader queue
                           blocking; attributed HERE only, never again
                           inside data/pipeline.py, to keep categories
                           disjoint)
  ``ckpt_blocking``        driver-blocking checkpoint windows (sync
                           save, async snapshot+enqueue, restore; the
                           background writer thread is deliberately
                           excluded — this ledger accounts the driver's
                           wall clock, not worker CPU time)
  ``retry_backoff``        faults.RetryPolicy sleep time on the driver
  ``elastic_reconfigure``  park -> rendezvous -> reinit -> restore
  ``anomaly_capture``      flightrec profiler start/stop overhead
  ``collective_skew``      health-boundary straggler wait (agree_health)
  ``other``                the explicit residual — reported, not hidden

Accounting contract: at every ``reconcile()`` (epoch boundary) and at
``close()``, ``sum(categories) + other == wall clock`` exactly, with
the residual fraction recorded per window.  The reconciliation target
is residual <= 1% of wall; the gate (scripts/goodput_gate.py) enforces
it on a canned run.

Non-overlap is enforced structurally, not by convention: ``timed()``
windows subtract time already attributed by nested hooks (e.g. a retry
sleep inside a checkpoint save counts once, as retry_backoff, and the
ckpt window shrinks by the same amount), and the step loop's
``step()`` charge does the same for its inter-step wait window.

Clock discipline: durations come from ``time.perf_counter`` only; the
persisted rows carry ``mono`` END stamps (``time.monotonic``) so
timeline.py can place them on the cross-rank timeline, plus a
``ts`` wall stamp for humans (never used in arithmetic — graftlint
rule 13 ``wall-clock-in-measurement`` enforces exactly this split).

Everything here is stdlib-only so faults/checkpoint/flightrec/elastic
can import it without cycles; /healthz runtime facts (world size,
elastic generation) are injected by the caller as callables.

Persistence: rank 0 writes ``RSL_PATH/goodput.json`` (the canonical
single-rank artifact); other ranks write ``goodput-rank<N>.json``.
``python main.py goodput`` aggregates whatever subset exists.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional

from . import telemetry

# The closed category set. "other" is the reconciliation residual and is
# never the target of an add() — it exists so the ledger sums to wall
# clock by construction instead of silently losing time.
CATEGORIES = (
    "compute",
    "compile",
    "data_wait",
    "ckpt_blocking",
    "retry_backoff",
    "elastic_reconfigure",
    "anomaly_capture",
    "collective_skew",
)
RESIDUAL = "other"


# -- ledger schema factories ------------------------------------------
#
# The persisted document shape is a CONTRACT shared by the live ledger
# below and the fleet simulator (sim/artifacts.py), which writes the
# same schema from a virtual clock.  Both go through these builders so
# `main.py goodput` / the timeline category track render simulated
# fleets unchanged.

def build_epoch_row(*, epoch: Optional[int], wall_s: float, mono: float,
                    ts: float, residual_s: float,
                    categories: Dict[str, float]) -> Dict[str, Any]:
    """One reconcile-window row of the ledger's ``epochs`` list; the
    rounding rules live here, once."""
    return {
        "epoch": epoch,
        "wall_s": round(wall_s, 6),
        "mono": mono,               # END stamp for timeline
        "ts": ts,                   # stamp only, for humans
        "residual_s": round(residual_s, 6),
        "residual_frac": (round(residual_s / wall_s, 6)
                          if wall_s > 0 else 0.0),
        "categories": {c: round(v, 6) for c, v in categories.items()},
    }


def build_ledger_doc(*, rank: int, world: int, started_ts: float,
                     wall_s: float, totals: Dict[str, float],
                     epochs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The persisted ledger document (also what /metrics reads live)."""
    accounted = sum(totals.values())
    return {
        "version": 1,
        "rank": int(rank),
        "world": int(world),
        "started_ts": started_ts,
        "wall_s": round(wall_s, 6),
        "accounted_s": round(accounted, 6),
        "residual_frac": (round((wall_s - accounted) / wall_s, 6)
                          if wall_s > 0 else 0.0),
        "categories": {c: round(v, 6) for c, v in totals.items()},
        "epochs": list(epochs),
    }


def ledger_filename(rank: int) -> str:
    """Rank 0 owns the canonical ``goodput.json``; other ranks write
    rank-suffixed files (no shared-file write races)."""
    return ("goodput.json" if rank == 0
            else "goodput-rank%d.json" % rank)


def write_ledger_doc(rsl_path: str, doc: Dict[str, Any]) -> Optional[str]:
    """Atomically persist one ledger document under ``rsl_path``;
    returns the path, or None on an unwritable disk (never raises —
    the ledger is observability, not training state)."""
    path = os.path.join(rsl_path, ledger_filename(int(doc.get("rank", 0))))
    tmp = path + ".tmp"
    try:
        os.makedirs(rsl_path, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:  # pragma: no cover - disk-full etc.
        logging.warning("goodput: write failed (%s) — ledger lost", e)
        return None
    return path


class GoodputLedger:
    """Per-process wall-clock attribution ledger.

    Disabled instances are no-ops on every path (the zero-cost contract
    shared with telemetry/flightrec).  Only main-thread contributions
    are recorded: a sleep on a producer thread is not driver wall time
    — the driver sees it (if at all) as data_wait through its own wait
    window, and counting both would break the sums-to-wall invariant.
    """

    def __init__(self, enabled: bool = False, rsl_path: Optional[str] = None,
                 rank: int = 0, world: int = 1):
        self.enabled = bool(enabled)
        self.rsl_path = rsl_path
        self.rank = int(rank)
        self.world = int(world)
        self._t0 = time.perf_counter()
        self._started_ts = time.time()  # stamp only, never subtracted
        self._totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._totals[RESIDUAL] = 0.0
        self._last: str = RESIDUAL
        # Nested-attribution bookkeeping (driver thread only — no lock):
        # stack of accumulators for open timed() windows, plus one
        # optional accumulator for the step loop's inter-step window.
        self._frames: List[float] = []
        self._step_nested: Optional[float] = None
        self._epochs: List[Dict[str, Any]] = []
        self._mark_wall = 0.0
        self._mark_totals: Dict[str, float] = dict(self._totals)
        self._closed = False

    # -- attribution --------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        """Attribute ``seconds`` of driver wall clock to ``category``.

        Off-main-thread calls are dropped (see class docstring); the
        innermost open window absorbs the charge so enclosing windows
        don't count it twice.
        """
        if not self.enabled or seconds <= 0.0:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        self._totals[category] += seconds
        self._last = category
        if self._frames:
            self._frames[-1] += seconds
        elif self._step_nested is not None:
            self._step_nested += seconds

    @contextmanager
    def timed(self, category: str) -> Iterator[None]:
        """Charge the body's elapsed time to ``category``, minus any
        time nested hooks already attributed (retry sleeps inside a
        checkpoint save count once, as retry_backoff)."""
        if not self.enabled:
            yield
            return
        self._frames.append(0.0)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            nested = self._frames.pop()
            self.add(category, max(0.0, dt - nested))

    def begin_steps(self) -> None:
        """Open the step loop's inter-step accounting window.  Call once
        at the top of each streaming step loop."""
        if self.enabled:
            self._step_nested = 0.0

    def step(self, dispatch_s: float, wait_s: float) -> str:
        """Per-step charge: dispatch -> compute, inter-step wait ->
        data_wait (minus time nested hooks already claimed from the
        wait window).  Returns the step's dominant category — this is
        what the flight recorder stores per ring slot."""
        if not self.enabled:
            return "compute" if dispatch_s >= wait_s else "data_wait"
        nested = self._step_nested or 0.0
        self._step_nested = 0.0
        wait = max(0.0, wait_s - nested)
        self.add("data_wait", wait)
        self.add("compute", max(0.0, dispatch_s))
        # The adds above landed in _step_nested; reset so the next
        # step's wait window is measured from zero.
        self._step_nested = 0.0
        return "compute" if dispatch_s >= wait else "data_wait"

    def end_steps(self) -> None:
        """Close the step loop's accounting window (end of epoch)."""
        self._step_nested = None

    def current(self) -> str:
        """The category this rank most recently spent time in — what a
        crash dump should say the rank was doing when it died."""
        return self._last

    # -- reconciliation & persistence ---------------------------------

    def reconcile(self, epoch: Optional[int] = None) -> Dict[str, Any]:
        """Close the accounting window since the previous reconcile:
        the window's unattributed time becomes an explicit ``other``
        charge so categories sum to wall clock exactly.  Returns the
        per-window row (also persisted)."""
        if not self.enabled:
            return {}
        wall = time.perf_counter() - self._t0
        window = wall - self._mark_wall
        deltas = {c: self._totals[c] - self._mark_totals.get(c, 0.0)
                  for c in self._totals}
        accounted = sum(deltas.values())
        residual = window - accounted
        # Attribute the residual explicitly; clamp tiny negative skew
        # (float rounding across thousands of adds) at zero.
        self._totals[RESIDUAL] += max(0.0, residual)
        deltas[RESIDUAL] += max(0.0, residual)
        row = build_epoch_row(epoch=epoch, wall_s=window,
                              mono=time.monotonic(), ts=time.time(),
                              residual_s=residual, categories=deltas)
        self._epochs.append(row)
        self._mark_wall = wall
        self._mark_totals = dict(self._totals)
        self._step_nested = None
        return row

    def snapshot(self) -> Dict[str, Any]:
        """The persisted document (also what /metrics reads live)."""
        wall = time.perf_counter() - self._t0
        return build_ledger_doc(rank=self.rank, world=self.world,
                                started_ts=self._started_ts,
                                wall_s=wall, totals=self._totals,
                                epochs=self._epochs)

    def write(self) -> Optional[str]:
        """Atomically persist the ledger under rsl_path (see
        :func:`write_ledger_doc` for the filename convention)."""
        if not self.enabled or not self.rsl_path:
            return None
        return write_ledger_doc(self.rsl_path, self.snapshot())

    def close(self) -> None:
        """Final reconcile (tail window after the last epoch) + write +
        disable.  Idempotent — elastic.quiesce_exit and the run_train
        finally block may both reach it."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        self.reconcile(epoch=None)
        self.write()
        self.enabled = False


# -- module-level singleton (mirrors telemetry/flightrec) -------------

_active = GoodputLedger(enabled=False)


def get() -> GoodputLedger:
    return _active


def configure(rsl_path: Optional[str], enabled: bool, rank: int = 0,
              world: int = 1) -> GoodputLedger:
    global _active
    if _active.enabled:
        _active.close()
    _active = GoodputLedger(enabled=enabled, rsl_path=rsl_path, rank=rank,
                            world=world)
    return _active


# -- reading & summarizing persisted ledgers --------------------------

def load_ledgers(rsl_path: str) -> Dict[int, Dict[str, Any]]:
    """All persisted ledgers under ``rsl_path``, keyed by rank."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(rsl_path))
    except OSError:
        return out
    for name in names:
        if name != "goodput.json" and not (
                name.startswith("goodput-rank") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(rsl_path, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            logging.warning("goodput: skipping unreadable %s (%s)", name, e)
            continue
        out[int(doc.get("rank", 0))] = doc
    return out


def report(rsl_path: str) -> str:
    """Human summary: per-rank attribution plus a fleet aggregate with
    the top badput cause called out.  Raises ValueError when no ledger
    exists (mirrors telemetry.report)."""
    ledgers = load_ledgers(rsl_path)
    if not ledgers:
        raise ValueError("no goodput ledger under %s — run with --telemetry "
                         "or --metrics-port" % rsl_path)
    lines: List[str] = ["goodput — wall-clock attribution (%s)" % rsl_path]
    fleet: Dict[str, float] = {}
    fleet_wall = 0.0
    order = list(CATEGORIES) + [RESIDUAL]
    for rank in sorted(ledgers):
        doc = ledgers[rank]
        wall = float(doc.get("wall_s", 0.0)) or 1e-9
        cats = doc.get("categories", {})
        fleet_wall += wall
        for c, v in cats.items():
            fleet[c] = fleet.get(c, 0.0) + float(v)
        lines.append("  rank %d — wall %.2fs, residual %.2f%%" % (
            rank, wall, 100.0 * float(doc.get("residual_frac", 0.0))))
        for c in order:
            v = float(cats.get(c, 0.0))
            if v > 0.0005:
                lines.append("    %-20s %8.2fs  %5.1f%%" % (
                    c, v, 100.0 * v / wall))
    fleet_wall = fleet_wall or 1e-9
    goodput = fleet.get("compute", 0.0)
    lines.append("  fleet — %d rank(s), wall %.2fs, goodput (compute) %.1f%%"
                 % (len(ledgers), fleet_wall, 100.0 * goodput / fleet_wall))
    badput = {c: v for c, v in fleet.items() if c != "compute" and v > 0}
    if badput:
        top = max(badput, key=lambda c: badput[c])
        lines.append("  top badput cause: %s (%.2fs, %.1f%% of wall)" % (
            top, badput[top], 100.0 * badput[top] / fleet_wall))
    # The ledger says WHERE the wall clock went; the roofline report
    # (when this run profiled) says WHICH op the compute share went to
    # — point at it so the two layers read as one story.
    rl_path = os.path.join(rsl_path, "roofline.json")
    try:
        with open(rl_path) as f:
            rl = json.load(f)
        tops = [r.get("name") for r in (rl.get("ops") or [])[:3]]
        lines.append(
            "  op-level blame: %s — top ops %s "
            "(%.1f%% of step time attributed; see `main.py roofline`)"
            % (rl_path, ", ".join(t for t in tops if t) or "-",
               100.0 * float(rl.get("coverage") or 0.0)))
    except (OSError, ValueError):
        pass
    return "\n".join(lines)


# -- live exporter (/metrics + /healthz) ------------------------------

def _prom_name(name: str) -> str:
    """Telemetry names are slash/dot-spaced ("data/wait_s"); Prometheus
    wants [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "dpt_" + s


class MetricsExporter:
    """Per-rank daemon-thread HTTP server: ``/metrics`` (Prometheus
    text exposition of all telemetry counters/gauges, histogram
    quantiles, and goodput category totals) and ``/healthz`` (rank,
    world size, elastic generation, last-step age as JSON).

    Scrape threads only read; the driver's only write is the
    ``note_step`` stamp, guarded by ``_lock``.  ``close()`` shuts the
    listener down and joins the serve thread — no leaked sockets or
    threads after run_train's finally block or elastic.quiesce_exit.
    """

    def __init__(self, port: int, rank: int = 0,
                 world_size_fn: Optional[Callable[[], int]] = None,
                 generation_fn: Optional[Callable[[], int]] = None):
        import http.server

        self.port = int(port)
        self.rank = int(rank)
        self._world_size_fn = world_size_fn or (lambda: 1)
        self._generation_fn = generation_fn or (lambda: 0)
        self._lock = threading.Lock()
        self._last_step_mono: Optional[float] = None  # guarded by _lock
        self._health_extra_fn: Optional[Callable[[], Dict[str, Any]]] \
            = None
        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.startswith("/metrics"):
                    body = exporter.render_metrics().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/healthz"):
                    body = json.dumps(exporter.health()).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes are high-frequency; keep the run log clean

        self._server = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.25},
            name="metrics-exporter", daemon=True)
        self._thread.start()

    # -- driver-side updates ------------------------------------------

    def note_step(self) -> None:
        """Stamp 'a train step just finished' for /healthz freshness."""
        with self._lock:
            self._last_step_mono = time.monotonic()

    # -- rendering (called from scrape threads) -----------------------

    def render_metrics(self) -> str:
        tel = telemetry.get()
        gp = get()
        lines: List[str] = []
        if tel.enabled:
            counters, gauges, histograms = tel.metrics_snapshot()
            for c in sorted(counters, key=lambda c: c.name):
                m = _prom_name(c.name) + "_total"
                lines.append("# TYPE %s counter" % m)
                lines.append("%s %.17g" % (m, c.value))
            for g in sorted(gauges, key=lambda g: g.name):
                if g.value is None:  # recorded-null gauge: nothing to scrape
                    continue
                m = _prom_name(g.name)
                lines.append("# TYPE %s gauge" % m)
                lines.append("%s %.17g" % (m, g.value))
            for h in sorted(histograms, key=lambda h: h.name):
                m = _prom_name(h.name)
                lines.append("# TYPE %s summary" % m)
                for q in (0.5, 0.95, 0.99):
                    lines.append('%s{quantile="%g"} %.17g'
                                 % (m, q, h.quantile(q)))
                lines.append("%s_count %d" % (m, h.count))
                lines.append("%s_sum %.17g" % (m, h.sum))
                if h.count:
                    # The sketch itself, as cumulative Prometheus-style
                    # buckets: le = the geometric upper boundary
                    # exp((idx+1)*log(1.02)).  Summary quantiles don't
                    # merge across ranks; these buckets do — the fleet
                    # collector reconstructs the sketch from this block
                    # (telemetry.Histogram.from_parts) and merge()s it
                    # bucket-wise, which is exact.
                    lines.append("%s_min %.17g" % (m, h.min))
                    lines.append("%s_max %.17g" % (m, h.max))
                    cum = h._nonpos
                    if cum:
                        lines.append('%s_bucket{le="0"} %d' % (m, cum))
                    growth = telemetry.Histogram._GROWTH_LOG
                    for idx in sorted(h._buckets):
                        cum += h._buckets[idx]
                        lines.append(
                            '%s_bucket{le="%.17g"} %d'
                            % (m, math.exp((idx + 1) * growth), cum))
                    lines.append('%s_bucket{le="+Inf"} %d'
                                 % (m, h.count))
        if gp.enabled:
            m = "dpt_goodput_seconds_total"
            lines.append("# TYPE %s counter" % m)
            for c, v in gp.snapshot()["categories"].items():
                lines.append('%s{category="%s"} %.17g' % (m, c, v))
        lines.append("# TYPE dpt_up gauge")
        lines.append("dpt_up 1")
        return "\n".join(lines) + "\n"

    def health(self) -> Dict[str, Any]:
        with self._lock:
            last = self._last_step_mono
        age = (time.monotonic() - last) if last is not None else None
        try:
            world = int(self._world_size_fn())
            generation = int(self._generation_fn())
        except Exception:  # runtime may be mid-reconfigure
            world, generation = -1, -1
        doc = {
            "status": "ok",
            "rank": self.rank,
            "world_size": world,
            "elastic_generation": generation,
            "last_step_age_s": round(age, 3) if age is not None else None,
        }
        extra = self._health_extra_fn
        if extra is not None:
            try:
                doc["serve"] = extra()
            except Exception:
                pass  # a failing stats callback must not break /healthz
        return doc

    def close(self) -> None:
        """Stop serving and release the socket.  Idempotent."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        self._thread.join(timeout=5.0)


_exporter: Optional[MetricsExporter] = None


def exporter() -> Optional[MetricsExporter]:
    return _exporter


def start_exporter(port: int, rank: int = 0,
                   world_size_fn: Optional[Callable[[], int]] = None,
                   generation_fn: Optional[Callable[[], int]] = None,
                   ) -> Optional[MetricsExporter]:
    """Bind ``port + rank`` (per-rank servers coexist on one host) and
    start serving.  A bind failure degrades to a warning — monitoring
    must never kill training."""
    global _exporter
    stop_exporter()
    try:
        _exporter = MetricsExporter(port + rank, rank=rank,
                                    world_size_fn=world_size_fn,
                                    generation_fn=generation_fn)
    except OSError as e:
        logging.warning("goodput: /metrics exporter disabled — cannot bind "
                        "port %d (%s)", port + rank, e)
        _exporter = None
    else:
        logging.info("goodput: serving /metrics and /healthz on :%d",
                     port + rank)
    return _exporter


def set_health_extra(fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
    """Attach an extra payload callable to /healthz (the serving tier
    reports queue depth + answered count there).  No-op when the
    exporter is disabled."""
    if _exporter is not None:
        _exporter._health_extra_fn = fn


def stop_exporter() -> None:
    global _exporter
    if _exporter is not None:
        _exporter.close()
        _exporter = None
