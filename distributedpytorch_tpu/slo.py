"""Declarative SLOs over fleet series: pure burn-rate evaluation.

An ``--slo-spec`` JSON file declares objectives over the fleet
collector's merged series (fleet.py) — the three shapes that cover the
serving tier's contract:

  ratio      a good/bad counter pair with an availability target and
             multi-window burn-rate alerting (the SRE playbook shape:
             error rate, shed rate).  burn = bad_fraction / (1-target);
             the objective fires only when EVERY window's burn exceeds
             its threshold — the short window proves it is happening
             NOW, the long window proves it is not a blip.
  quantile   a latency histogram objective (e.g. p95 request latency
             <= 250ms) evaluated on the WINDOWED delta sketch, not the
             lifetime sketch — a startup spike must not page forever.
  share      a goodput category's share of wall time over the window
             (e.g. compute share >= 0.5) from the merged
             dpt_goodput_seconds_total counters.

Spec example (the worked example in README.md)::

    {"slos": [
      {"name": "serve-errors", "kind": "ratio",
       "bad": "dpt_serve_failed_total",
       "total": "dpt_serve_requests_total",
       "target": 0.99,
       "windows": [{"seconds": 10, "burn": 2.0},
                   {"seconds": 60, "burn": 1.0}]},
      {"name": "latency-p95", "kind": "quantile",
       "series": "dpt_serve_request_latency_ms", "q": 0.95,
       "max": 250.0, "windows": [{"seconds": 30}]}
    ]}

THE design constraint (ISSUE 16): ``evaluate()`` is a pure function of
(spec, sample window).  No wall-clock reads, no sockets, no process
state — every sample carries its own ordering time ``t``, stamped by
whoever produced it (the fleet collector live, a test by hand, the
future fleet simulator synthetically).  Same spec + same window =>
identical verdicts, so the autoscaler controller and the simulator
(ROADMAP open items) consume this module unchanged, and graftlint rule
13 stays clean here by construction.

Samples are fleet.py cycle records::

    {"t": <ordering seconds>, "counters": {prom_key: value},
     "histograms": {name: {"count","sum","min","max","nonpos",
                           "buckets": {idx: n}}}}

Counter keys are full Prometheus keys including labels
(``dpt_goodput_seconds_total{category="compute"}``), so ``share``
objectives are just a labeled-counter family sum.  Windowed deltas are
clamped at zero: an elastic rank ageing out can shrink a merged
cumulative sum, and a shrink must read as "no new events", never as
negative traffic.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry

KINDS = ("ratio", "quantile", "share")

_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")

#: the goodput counter family share objectives sum over.
GOODPUT_FAMILY = "dpt_goodput_seconds_total"


# -- spec --------------------------------------------------------------

def validate_spec(spec: Any) -> List[Dict[str, Any]]:
    """Validate a parsed spec, returning its objective list.  Every
    rejection is ONE actionable line naming the offending objective —
    a spec error at fleet startup must read like a fix, not a trace."""
    if not isinstance(spec, dict) or not isinstance(spec.get("slos"),
                                                    list):
        raise ValueError(
            "slo spec must be an object with an 'slos' list")
    if not spec["slos"]:
        raise ValueError("slo spec declares no objectives ('slos' is "
                         "empty) — delete the flag or add one")
    out: List[Dict[str, Any]] = []
    seen: set = set()
    for i, slo in enumerate(spec["slos"]):
        where = f"slos[{i}]"
        if not isinstance(slo, dict):
            raise ValueError(f"{where}: objective must be an object")
        name = slo.get("name")
        if not isinstance(name, str) or not _NAME_RE.match(name):
            raise ValueError(
                f"{where}: 'name' must match [A-Za-z0-9._-]+ (it names "
                f"the incident bundle file), got {name!r}")
        where = f"slos[{i}] {name!r}"
        if name in seen:
            raise ValueError(f"{where}: duplicate objective name")
        seen.add(name)
        kind = slo.get("kind")
        if kind not in KINDS:
            raise ValueError(
                f"{where}: 'kind' must be one of {list(KINDS)}, "
                f"got {kind!r}")
        windows = slo.get("windows")
        if not isinstance(windows, list) or not windows:
            raise ValueError(
                f"{where}: 'windows' must be a non-empty list of "
                f"{{'seconds': s}} objects")
        for j, w in enumerate(windows):
            if not isinstance(w, dict) \
                    or not isinstance(w.get("seconds"), (int, float)) \
                    or w["seconds"] <= 0:
                raise ValueError(
                    f"{where}: windows[{j}] needs 'seconds' > 0")
        if kind == "ratio":
            for key in ("bad", "total"):
                if not isinstance(slo.get(key), str) or not slo[key]:
                    raise ValueError(
                        f"{where}: ratio objectives need a {key!r} "
                        f"counter key (a fleet /metrics series name)")
            target = slo.get("target")
            if not isinstance(target, (int, float)) \
                    or not 0.0 < target < 1.0:
                raise ValueError(
                    f"{where}: 'target' must be in (0, 1) — it is the "
                    f"availability objective, e.g. 0.99")
            for j, w in enumerate(windows):
                if not isinstance(w.get("burn"), (int, float)) \
                        or w["burn"] <= 0:
                    raise ValueError(
                        f"{where}: windows[{j}] needs 'burn' > 0 "
                        f"(the burn-rate threshold for that window)")
        elif kind == "quantile":
            if not isinstance(slo.get("series"), str) \
                    or not slo["series"]:
                raise ValueError(
                    f"{where}: quantile objectives need a 'series' "
                    f"histogram name (e.g. dpt_serve_request_latency_ms)")
            q = slo.get("q")
            if not isinstance(q, (int, float)) or not 0.0 < q < 1.0:
                raise ValueError(
                    f"{where}: 'q' must be in (0, 1), e.g. 0.95")
            if not isinstance(slo.get("max"), (int, float)) \
                    or slo["max"] <= 0:
                raise ValueError(
                    f"{where}: 'max' must be > 0 (the latency bound in "
                    f"the series' own unit)")
        else:  # share
            if not isinstance(slo.get("category"), str) \
                    or not slo["category"]:
                raise ValueError(
                    f"{where}: share objectives need a goodput "
                    f"'category' (compute/input/checkpoint/...)")
            mn = slo.get("min")
            if not isinstance(mn, (int, float)) or not 0.0 < mn <= 1.0:
                raise ValueError(
                    f"{where}: 'min' must be in (0, 1] — the category's "
                    f"minimum share of windowed goodput seconds")
    return list(spec["slos"])


def load_spec(path: str) -> List[Dict[str, Any]]:
    """Read + validate a spec file; errors carry the path."""
    try:
        with open(path, encoding="utf-8") as f:
            spec = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read slo spec {path!r}: {e}")
    except ValueError as e:
        raise ValueError(f"slo spec {path!r} is not valid JSON: {e}")
    try:
        return validate_spec(spec)
    except ValueError as e:
        raise ValueError(f"slo spec {path!r}: {e}")


# -- windowed deltas ---------------------------------------------------

def _window(samples: List[Dict[str, Any]], seconds: float
            ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(baseline, latest) samples for a trailing window: the baseline is
    the newest sample at least ``seconds`` older than the latest, or
    the oldest sample when the series is younger than the window — a
    fast-burn objective must be able to fire before a long history
    exists."""
    latest = samples[-1]
    cutoff = float(latest["t"]) - float(seconds)
    base = samples[0]
    for s in samples:
        if float(s["t"]) <= cutoff:
            base = s
        else:
            break
    return base, latest


def counter_delta(samples: List[Dict[str, Any]], key: str,
                  seconds: float) -> float:
    """Windowed increase of a merged counter, clamped at zero (an
    elastic shrink is 'no new events', not negative traffic)."""
    base, latest = _window(samples, seconds)
    return max(0.0, float(latest.get("counters", {}).get(key, 0.0))
               - float(base.get("counters", {}).get(key, 0.0)))


def _sketch_delta(base: Dict[str, Any], latest: Dict[str, Any],
                  series: str) -> Optional[telemetry.Histogram]:
    """The window's own histogram: latest state minus baseline state,
    bucket-wise.  Exact for the sketch, same as merge()."""
    end = latest.get("histograms", {}).get(series)
    if not end:
        return None
    start = base.get("histograms", {}).get(series) or {}
    sb = {int(k): int(v) for k, v in (start.get("buckets") or {}).items()}
    buckets: Dict[int, int] = {}
    for k, v in (end.get("buckets") or {}).items():
        d = int(v) - sb.get(int(k), 0)
        if d > 0:
            buckets[int(k)] = d
    nonpos = max(0, int(end.get("nonpos", 0)) - int(start.get("nonpos",
                                                              0)))
    count = nonpos + sum(buckets.values())
    if count <= 0:
        return None
    # min/max are lifetime extremes, not windowed — the delta sketch's
    # clamp range comes from its own occupied buckets instead (within
    # the sketch's 2% bound by construction).
    growth = telemetry.Histogram._GROWTH_LOG
    if buckets:
        lo = math.exp(min(buckets) * growth)
        hi = math.exp((max(buckets) + 1) * growth)
    else:
        lo = hi = 0.0
    total = float(end.get("sum", 0.0)) - float(start.get("sum", 0.0))
    return telemetry.Histogram.from_parts(
        series, count, total, lo, hi, buckets, nonpos=nonpos)


def windowed_quantile(samples: List[Dict[str, Any]], series: str,
                      q: float, seconds: float) -> Optional[float]:
    """The q-quantile of observations that landed INSIDE the trailing
    window, from the delta sketch (None = no observations)."""
    base, latest = _window(samples, seconds)
    sketch = _sketch_delta(base, latest, series)
    return sketch.quantile(q) if sketch is not None else None


# -- evaluation --------------------------------------------------------

def evaluate(slos: List[Dict[str, Any]],
             samples: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One verdict per objective over the sample window.  Pure: the
    only time that exists here is the ``t`` the samples carry.  An
    objective fires when EVERY window exceeds its threshold; fewer than
    two samples means nothing can burn yet (no deltas exist)."""
    verdicts: List[Dict[str, Any]] = []
    ready = len(samples) >= 2
    for slo in slos:
        windows: List[Dict[str, Any]] = []
        firing = ready
        for w in slo["windows"]:
            seconds = float(w["seconds"])
            detail: Dict[str, Any] = {"seconds": seconds}
            exceeded = False
            if ready:
                base, latest = _window(samples, seconds)
                detail["t_start"] = float(base["t"])
                detail["t_end"] = float(latest["t"])
                if slo["kind"] == "ratio":
                    bad = counter_delta(samples, slo["bad"], seconds)
                    total = counter_delta(samples, slo["total"], seconds)
                    burn = ((bad / total) / (1.0 - float(slo["target"]))
                            if total > 0 else 0.0)
                    detail.update(bad=bad, total=total,
                                  value=round(burn, 6),
                                  threshold=float(w["burn"]))
                    exceeded = total > 0 and burn >= float(w["burn"])
                elif slo["kind"] == "quantile":
                    val = windowed_quantile(samples, slo["series"],
                                            float(slo["q"]), seconds)
                    detail.update(
                        value=None if val is None else round(val, 6),
                        threshold=float(slo["max"]))
                    exceeded = val is not None and val > float(slo["max"])
                else:  # share
                    prefix = GOODPUT_FAMILY + "{"
                    keys = [k for k in samples[-1].get("counters", {})
                            if k.startswith(prefix)]
                    deltas = {k: counter_delta(samples, k, seconds)
                              for k in keys}
                    whole = sum(deltas.values())
                    want = '%s{category="%s"}' % (GOODPUT_FAMILY,
                                                  slo["category"])
                    share = (deltas.get(want, 0.0) / whole
                             if whole > 0 else None)
                    detail.update(
                        value=None if share is None else round(share, 6),
                        threshold=float(slo["min"]))
                    exceeded = share is not None \
                        and share < float(slo["min"])
            detail["exceeded"] = exceeded
            windows.append(detail)
            firing = firing and exceeded
        verdicts.append({"name": slo["name"], "kind": slo["kind"],
                         "firing": firing, "windows": windows})
    return verdicts


# -- incident reporting (main.py incidents) ----------------------------

def load_incidents(rsl_path: str) -> List[Dict[str, Any]]:
    """Every incident bundle the fleet collector wrote under the run
    dir, in firing order."""
    bundles: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(rsl_path,
                                              "incident-*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        doc["_path"] = os.path.basename(path)
        bundles.append(doc)
    return bundles


def incidents_report(rsl_path: str) -> str:
    """Human-readable digest of the run's incident bundles."""
    bundles = load_incidents(rsl_path)
    if not bundles:
        return ("no incidents: no SLO objective fired during this run "
                f"(searched {os.path.join(rsl_path, 'incident-*.json')})")
    lines = [f"{len(bundles)} incident(s):", ""]
    for b in bundles:
        lines.append(f"== {b.get('_path')} — objective "
                     f"{b.get('slo')!r} ({b.get('kind')}) fired at "
                     f"cycle {b.get('cycle')}")
        for w in b.get("windows", []):
            lines.append(
                f"   window {w.get('seconds')}s: value "
                f"{w.get('value')} vs threshold {w.get('threshold')} "
                f"(t {w.get('t_start')} -> {w.get('t_end')})")
        suspects = b.get("suspect_ranks", [])
        lines.append(f"   suspect ranks: "
                     f"{suspects if suspects else '(none isolated)'}")
        ids = b.get("offending_requests", [])
        if ids:
            shown = ", ".join(ids[:8])
            more = f" (+{len(ids) - 8} more)" if len(ids) > 8 else ""
            lines.append(f"   offending requests: {shown}{more}")
        health = b.get("healthz", {})
        for rank in sorted(health, key=str):
            doc = health[rank]
            lines.append(f"   rank {rank} healthz: {json.dumps(doc)}"
                         if doc else f"   rank {rank} healthz: (down)")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"
