"""L2: cross-rank timeline — telemetry + flight records as one trace.

``python main.py timeline --rsl_path RSL`` merges every rank's telemetry
JSONL (telemetry/rank*.jsonl) and flight-recorder dump
(flightrec-rank*.json) into a single Chrome trace-event file that
Perfetto (https://ui.perfetto.dev) or chrome://tracing loads directly:
one process row per rank, telemetry spans and flight-recorder steps on
separate threads, point events (anomaly, fault_injected, preempt_signal,
health_boundary) as instants, and — when the run wrote a goodput ledger
(goodput*.json) — a per-rank category track: one slice per reconcile
window named by its dominant category plus a stacked counter series of
the full category mix.

Clock alignment.  Each rank stamps records with its own ``mono`` clock,
whose origin is arbitrary per process — raw mono values from two ranks
are not comparable.  Wall clocks (``ts``) are comparable but can be
skewed between hosts.  The merger therefore aligns on the PR 4 health
allgather: ``cli._health_boundary`` emits a ``health_boundary`` event on
every rank immediately after ``runtime.agree_health`` returns, and a
blocking allgather returns at (nearly) the same real instant everywhere —
so for each epoch boundary e, mono_r(e) on every rank r names the same
physical moment.  Rank r's offset onto rank 0's mono axis is the median
over shared boundaries of ``mono_0(e) - mono_r(e)``; the median makes one
straggly boundary (a rank that lingered in the allgather) harmless.
Runs without shared boundaries (single rank, --no-health-checks) fall
back to wall-clock alignment via each rank's median ``ts - mono`` delta —
correct up to host clock skew, which the skew report then quantifies.
The fallback is per rank ("mixed" mode): one boundary-less stream — a
rank that died mid-epoch before its first boundary, the elastic
rank-loss shape — degrades only itself, and an ``elastic/reconfigure``
boundary in the events is surfaced as a survivors/departed warning
rather than a crash or silent truncation.

Grown worlds.  A rank that JOINS mid-run (elastic grow) announces
itself with an ``elastic/join`` event — and when it is a departed rank
restarting, it appends to the departed incarnation's telemetry file.
The two incarnations have different mono origins, so alignment cuts at
the join instant: boundary offsets use only post-join boundaries (the
joined rank aligns from its first health-boundary), and the pre-join
segment is re-anchored by its own wall clock with a warning.  The
reconfigure warning names joined ranks alongside departed ones.

Skew report.  At every shared boundary the ranks' *wall* stamps should
agree too; their spread (max - min) is the measured cross-rank wall-clock
skew per epoch, reported per boundary and as a maximum.  The straggler
table attributes per-rank time: mean epoch span, mean step time and
data-wait share from the flight records — the rank that is slow because
it waits on data reads differently from the rank that is slow dispatching.

Hostile inputs degrade, never crash: a missing flight record for one rank
drops to telemetry-only for that rank (warning in the summary), torn
JSONL tails are skipped line-wise, and a run directory with no telemetry
at all is a one-line actionable error (``ValueError``).
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Any, Dict, List, Optional, Tuple

from . import flightrec, goodput, telemetry, tracing

# Thread ids within each rank's process row.
_TID_SPANS = 0      # telemetry spans
_TID_STEPS = 1      # flight-recorder per-step records
_TID_EVENTS = 2     # point events / instants
_TID_GOODPUT = 3    # goodput ledger: per-epoch category attribution
_TID_REQUESTS = 4   # serving tier: per-request trace span chains


def _attrs(ev: Dict[str, Any]) -> Dict[str, Any]:
    a = ev.get("attrs")
    return a if isinstance(a, dict) else {}


def _goodput_rows(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The plottable per-window rows of one rank's ledger: mono END
    stamp, positive wall_s window, and a category map — anything torn
    or hand-edited is dropped, never crashed on."""
    rows = []
    for row in doc.get("epochs", []):
        if not isinstance(row, dict) \
                or not isinstance(row.get("mono"), (int, float)) \
                or not isinstance(row.get("wall_s"), (int, float)) \
                or not isinstance(row.get("categories"), dict):
            continue
        if float(row["wall_s"]) <= 0:
            continue
        rows.append({"epoch": row.get("epoch"),
                     "mono": float(row["mono"]),
                     "wall_s": float(row["wall_s"]),
                     "residual_s": row.get("residual_s"),
                     "categories": {str(k): float(v)
                                    for k, v in row["categories"].items()
                                    if isinstance(v, (int, float))}})
    return rows


def _boundaries(events: List[Dict[str, Any]],
                cuts: Optional[Dict[int, float]] = None
                ) -> Dict[int, Dict[int, Dict[str, float]]]:
    """rank -> epoch -> {"ts","mono"} for every health_boundary event.
    A rank that emitted the same epoch twice keeps the last stamp (a
    resumed run re-walks earlier epochs).  ``cuts`` (rank -> wall ts of
    its last ``elastic/join``) drops boundaries stamped BEFORE a rank
    rejoined: those belong to the departed incarnation, whose mono
    origin is unrelated to the rejoined process's."""
    out: Dict[int, Dict[int, Dict[str, float]]] = {}
    for ev in events:
        if ev.get("kind") != "event" or ev.get("name") != "health_boundary":
            continue
        try:
            rank = int(ev["rank"])
            epoch = int(_attrs(ev)["epoch"])
            stamp = {"ts": float(ev["ts"]), "mono": float(ev["mono"])}
        except (KeyError, TypeError, ValueError):
            continue
        if cuts and stamp["ts"] < cuts.get(rank, float("-inf")):
            continue
        out.setdefault(rank, {})[epoch] = stamp
    return out


def _join_cuts(events: List[Dict[str, Any]]) -> Dict[int, float]:
    """rank -> wall ts of that rank's LAST ``elastic/join`` event: the
    instant a mid-run joiner's stream (re)started.  A rejoining rank
    appends to the departed incarnation's telemetry file, so records
    before the cut carry a different mono origin than records after."""
    cuts: Dict[int, float] = {}
    for ev in events:
        if ev.get("kind") != "event" or ev.get("name") != "elastic/join":
            continue
        rank, ts = ev.get("rank"), ev.get("ts")
        if isinstance(rank, int) and isinstance(ts, (int, float)):
            cuts[rank] = max(float(ts), cuts.get(rank, float("-inf")))
    return cuts


def _wall_delta(events: List[Dict[str, Any]], rank: int,
                lo: Optional[float] = None,
                hi: Optional[float] = None) -> Optional[float]:
    """Median ``ts - mono`` for one rank: maps its mono clock onto its
    own wall clock (the no-boundary fallback alignment).  ``lo``/``hi``
    bound the wall stamps considered — used to keep a rejoined rank's
    two incarnations (different mono origins) from polluting each
    other's delta."""
    deltas = [float(ev["ts"]) - float(ev["mono"]) for ev in events
              if ev.get("rank") == rank
              and isinstance(ev.get("ts"), (int, float))
              and isinstance(ev.get("mono"), (int, float))
              and (lo is None or float(ev["ts"]) >= lo)
              and (hi is None or float(ev["ts"]) < hi)]
    return statistics.median(deltas) if deltas else None


def _alignment(events: List[Dict[str, Any]], ranks: List[int],
               cuts: Optional[Dict[int, float]] = None
               ) -> Tuple[Dict[int, float], str, List[str]]:
    """Per-rank offset to add to that rank's mono stamps so all ranks
    share one time axis.  Returns (offsets, method, warnings).

    Alignment is PER RANK, not all-or-nothing: a single rank with no
    shared boundary (one that died before its first health_boundary —
    the elastic rank-loss shape — or a freshly joined stream) falls
    back to its own wall clock with a warning naming it, while every
    other rank keeps the precise boundary alignment.  Method is
    "health_boundary" when every rank aligned on boundaries,
    "wall_clock" when none could, "mixed" otherwise.  In mixed mode
    every offset targets the WALL axis (boundary offsets are shifted by
    the base rank's own ts-mono delta) so the two kinds of offset land
    on one comparable axis.

    A rank with a join cut (see :func:`_join_cuts`) aligns from its
    first POST-join health boundary; its pre-join segment gets a
    separate wall-clock offset in :func:`build_timeline`.
    """
    cuts = cuts or {}
    warnings: List[str] = []
    bounds = _boundaries(events, cuts)
    base = min(ranks)
    boundary_offsets: Dict[int, float] = {}
    fallback: List[int] = []
    if base in bounds and len(ranks) > 1:
        boundary_offsets[base] = 0.0
        for r in ranks:
            if r == base:
                continue
            shared = sorted(set(bounds.get(r, {})) & set(bounds[base]))
            if shared:
                boundary_offsets[r] = statistics.median(
                    bounds[base][e]["mono"] - bounds[r][e]["mono"]
                    for e in shared)
            else:
                fallback.append(r)
        if not fallback:
            return boundary_offsets, "health_boundary", warnings
        if len(boundary_offsets) > 1:
            # Mixed: most ranks align precisely; the boundary-less ones
            # (truncated by a mid-epoch death, typically) ride their own
            # wall clock — comparable up to host clock skew.
            for r in fallback:
                warnings.append(
                    f"clock alignment: rank {r} shares no "
                    f"health_boundary with rank {base} (stream "
                    "truncated before its first boundary?); aligning "
                    "it by wall clock only")
            base_delta = _wall_delta(events, base, lo=cuts.get(base))
            if base_delta is not None:
                offsets = {r: off + base_delta
                           for r, off in boundary_offsets.items()}
                for r in fallback:
                    d = _wall_delta(events, r, lo=cuts.get(r))
                    offsets[r] = d if d is not None else base_delta
                return offsets, "mixed", warnings
            # base has no usable ts/mono pairs at all — degenerate;
            # drop to the uniform wall-clock fallback below.
        warnings.append("clock alignment: not every rank shares a "
                        "health_boundary with rank "
                        f"{base}; falling back to wall clocks")
    # Fallback: project every rank onto its own wall clock.  Correct up
    # to host clock skew (single-rank runs trivially so).
    offsets = {}
    for r in ranks:
        d = _wall_delta(events, r, lo=cuts.get(r))
        offsets[r] = d if d is not None else 0.0
    return offsets, "wall_clock", warnings


def _skew_report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-rank wall-clock spread at each shared boundary epoch."""
    bounds = _boundaries(events)
    per_epoch: Dict[int, float] = {}
    epochs = set()
    for stamps in bounds.values():
        epochs |= set(stamps)
    for e in sorted(epochs):
        walls = [stamps[e]["ts"] for stamps in bounds.values()
                 if e in stamps]
        if len(walls) >= 2:
            per_epoch[e] = max(walls) - min(walls)
    return {
        "boundary_epochs": sorted(epochs),
        "wall_skew_s_per_epoch": {str(e): round(v, 6)
                                  for e, v in per_epoch.items()},
        "max_wall_skew_s": (round(max(per_epoch.values()), 6)
                            if per_epoch else None),
    }


def _stragglers(events: List[Dict[str, Any]],
                dumps: Dict[int, Dict[str, Any]],
                ranks: List[int]) -> List[Dict[str, Any]]:
    """Per-rank attribution rows; the slowest mean epoch is flagged."""
    rows: List[Dict[str, Any]] = []
    for r in ranks:
        epoch_durs = [float(ev["dur_s"]) for ev in events
                      if ev.get("kind") == "span"
                      and ev.get("name") == "epoch"
                      and ev.get("rank") == r
                      and isinstance(ev.get("dur_s"), (int, float))]
        steps = [rec for rec in dumps.get(r, {}).get("records", [])
                 if isinstance(rec, dict) and rec.get("kind") == "step"]
        step_s = [float(s["step_s"]) for s in steps
                  if isinstance(s.get("step_s"), (int, float))]
        wait_s = [float(s["wait_s"]) for s in steps
                  if isinstance(s.get("wait_s"), (int, float))]
        row: Dict[str, Any] = {
            "rank": r,
            "epochs_seen": len(epoch_durs),
            "mean_epoch_s": (round(statistics.mean(epoch_durs), 6)
                             if epoch_durs else None),
            "steps_recorded": len(steps),
            "mean_step_s": (round(statistics.mean(step_s), 6)
                            if step_s else None),
            "data_wait_share": (round(sum(wait_s) / max(sum(step_s), 1e-12),
                                      4) if wait_s and step_s else None),
        }
        rows.append(row)
    timed = [row for row in rows if row["mean_epoch_s"] is not None]
    if timed:
        slowest = max(timed, key=lambda row: row["mean_epoch_s"])
        slowest["straggler"] = True
    return rows


def build_timeline(rsl_path: str) -> Dict[str, Any]:
    """Merge one run directory into {trace, skew, stragglers, ...}.

    Raises ``ValueError`` (one actionable line) when the run has no
    telemetry at all; every lesser defect degrades with a warning."""
    events = telemetry.load_events(os.path.join(rsl_path, "telemetry"))
    dumps = flightrec.load_dumps(rsl_path)
    ledgers = goodput.load_ledgers(rsl_path)
    requests = [r for r in tracing.load_records(rsl_path)
                if isinstance(r.get("rank"), int)
                and isinstance(r.get("mono_admit"), (int, float))]
    ranks = sorted({int(ev["rank"]) for ev in events
                    if isinstance(ev.get("rank"), int)} | set(dumps)
                   | {int(r["rank"]) for r in requests})
    if not ranks:
        raise ValueError(
            f"telemetry under {rsl_path!r} has no rank-stamped events; "
            "was it produced by an older build? re-run with --telemetry")
    cuts = _join_cuts(events)
    offsets, method, warnings = _alignment(events, ranks, cuts)
    # A rejoined rank's pre-join segment (the departed incarnation's
    # records, same file, different mono origin) gets its own offset:
    # its own wall clock, shifted onto whatever axis `offsets` targets.
    pre_offsets: Dict[int, float] = {}
    if cuts:
        base = min(ranks)
        base_delta = (_wall_delta(events, base, lo=cuts.get(base))
                      if method == "health_boundary" else None)
        for r, cut in sorted(cuts.items()):
            pre_delta = _wall_delta(events, r, hi=cut)
            if pre_delta is None:
                continue  # fresh joiner: no pre-join records at all
            if method == "health_boundary":
                if base_delta is None:
                    warnings.append(
                        f"clock alignment: rank {r} rejoined mid-run but "
                        f"base rank {base} has no usable wall stamps; its "
                        "pre-join segment may be misplaced")
                    continue
                pre_offsets[r] = (pre_delta - base_delta
                                  + offsets.get(base, 0.0))
            else:  # mixed / wall_clock: offsets already target wall time
                pre_offsets[r] = pre_delta
            warnings.append(
                f"clock alignment: rank {r} rejoined mid-run (elastic "
                "grow); its pre-join segment is aligned by wall clock "
                "only")
    for r in ranks:
        if r not in dumps:
            warnings.append(f"no flight record for rank {r} "
                            f"(flightrec-rank{r}.json missing/unreadable); "
                            "timeline shows telemetry spans only")
    if not ledgers:
        warnings.append("no goodput ledger (goodput*.json missing — run "
                        "predates the ledger or was killed before its "
                        "final write); timeline omits the category track")
    # Elastic reconfigure boundary (elastic.py): every survivor emits an
    # elastic/reconfigure event; a rank present in the run but absent
    # from that set is the departed one — its stream simply truncates at
    # the failure.  Named here so a shrunken-world trace reads as a
    # reconfigure, not as data loss.
    reconf = [ev for ev in events
              if ev.get("kind") == "event"
              and ev.get("name") == "elastic/reconfigure"
              and isinstance(ev.get("rank"), int)]
    if reconf:
        survivors = sorted({int(ev["rank"]) for ev in reconf})
        joined = sorted(set(cuts) & set(ranks))
        departed = sorted(set(ranks) - set(survivors) - set(joined))
        gens = sorted({_attrs(ev).get("generation") for ev in reconf
                       if _attrs(ev).get("generation") is not None})
        dep_note = (f"; rank(s) {departed} departed — their streams "
                    "truncate at the failure, which is expected, not "
                    "data loss" if departed else "")
        if joined:
            warnings.append(
                f"elastic reconfigure (generation(s) {gens}): survivors "
                f"{survivors} continued across the world change(s); "
                f"rank(s) {joined} joined in a grow generation — their "
                "streams begin (or restart) mid-run" + dep_note)
        else:
            warnings.append(
                f"elastic reconfigure (generation(s) {gens}): survivors "
                f"{survivors} continued in a smaller world" + dep_note)

    def aligned(rank: int, mono: float,
                ts: Optional[float] = None) -> float:
        if ts is not None and rank in pre_offsets \
                and ts < cuts.get(rank, float("-inf")):
            return mono + pre_offsets[rank]
        return mono + offsets.get(rank, 0.0)

    # First pass: the trace origin is the earliest aligned stamp so every
    # Chrome ts is non-negative.
    stamps: List[float] = []
    for ev in events:
        if isinstance(ev.get("mono"), (int, float)) \
                and isinstance(ev.get("rank"), int):
            wall = (float(ev["ts"])
                    if isinstance(ev.get("ts"), (int, float)) else None)
            t = aligned(ev["rank"], float(ev["mono"]), wall)
            if ev.get("kind") == "span" \
                    and isinstance(ev.get("dur_s"), (int, float)):
                t -= float(ev["dur_s"])  # span stamps are END stamps
            stamps.append(t)
    for r, doc in dumps.items():
        for rec in doc.get("records", []):
            if isinstance(rec, dict) \
                    and isinstance(rec.get("mono"), (int, float)):
                t = aligned(r, float(rec["mono"]))
                if isinstance(rec.get("step_s"), (int, float)):
                    t -= float(rec["step_s"])
                stamps.append(t)
    for r, doc in ledgers.items():
        for row in _goodput_rows(doc):
            # Ledger rows carry END stamps; the slice starts wall_s back.
            stamps.append(aligned(r, row["mono"] - row["wall_s"]))
    for rec in requests:
        stamps.append(aligned(int(rec["rank"]), float(rec["mono_admit"]),
                              rec.get("ts_admit")))
    if not stamps:
        raise ValueError(
            f"no timestamped records under {rsl_path!r}; nothing to plot")
    origin = min(stamps)

    def us(rank: int, mono: float, ts: Optional[float] = None) -> float:
        return round((aligned(rank, float(mono), ts) - origin) * 1e6, 3)

    trace_events: List[Dict[str, Any]] = []
    for r in ranks:
        trace_events.append({"ph": "M", "name": "process_name", "pid": r,
                             "args": {"name": f"rank{r}"}})
        trace_events.append({"ph": "M", "name": "process_sort_index",
                             "pid": r, "args": {"sort_index": r}})
        for tid, label in ((_TID_SPANS, "telemetry spans"),
                           (_TID_STEPS, "flightrec steps"),
                           (_TID_EVENTS, "events"),
                           (_TID_GOODPUT, "goodput categories"),
                           (_TID_REQUESTS, "requests")):
            if tid == _TID_GOODPUT and r not in ledgers:
                continue
            if tid == _TID_REQUESTS and not any(
                    int(rec["rank"]) == r for rec in requests):
                continue
            trace_events.append({"ph": "M", "name": "thread_name",
                                 "pid": r, "tid": tid,
                                 "args": {"name": label}})

    for ev in events:
        r = ev.get("rank")
        mono = ev.get("mono")
        if not isinstance(r, int) or not isinstance(mono, (int, float)):
            continue
        kind = ev.get("kind")
        wall = (float(ev["ts"])
                if isinstance(ev.get("ts"), (int, float)) else None)
        if kind == "span" and isinstance(ev.get("dur_s"), (int, float)):
            dur = float(ev["dur_s"])
            trace_events.append({
                "ph": "X", "cat": "telemetry",
                "name": str(ev.get("name", "span")), "pid": r,
                "tid": _TID_SPANS,
                "ts": us(r, float(mono) - dur, wall),
                "dur": round(dur * 1e6, 3),
                "args": _attrs(ev),
            })
        elif kind == "event":
            trace_events.append({
                "ph": "i", "cat": "telemetry", "s": "p",
                "name": str(ev.get("name", "event")), "pid": r,
                "tid": _TID_EVENTS, "ts": us(r, mono, wall),
                "args": _attrs(ev),
            })
    for r, doc in dumps.items():
        for rec in doc.get("records", []):
            if not isinstance(rec, dict) \
                    or not isinstance(rec.get("mono"), (int, float)):
                continue
            if rec.get("kind") == "step" \
                    and isinstance(rec.get("step_s"), (int, float)):
                dur = float(rec["step_s"])
                args = {k: rec[k] for k in ("epoch", "step", "dispatch_s",
                                            "wait_s", "queue_depth")
                        if k in rec}
                trace_events.append({
                    "ph": "X", "cat": "flightrec", "name": "step",
                    "pid": r, "tid": _TID_STEPS,
                    "ts": us(r, float(rec["mono"]) - dur),
                    "dur": round(dur * 1e6, 3), "args": args,
                })
            elif rec.get("kind") == "event":
                trace_events.append({
                    "ph": "i", "cat": "flightrec", "s": "p",
                    "name": str(rec.get("name", "event")), "pid": r,
                    "tid": _TID_EVENTS, "ts": us(r, rec["mono"]),
                    "args": {k: v for k, v in rec.items()
                             if k not in ("kind", "name", "ts", "mono")},
                })
    # Goodput ledger track: one slice per reconcile window, named by the
    # window's dominant category (full map in args), plus a Chrome
    # counter ("C") event per window so Perfetto draws the category mix
    # as a stacked area over the run.
    for r, doc in ledgers.items():
        for row in _goodput_rows(doc):
            cats = row["categories"]
            start = us(r, row["mono"] - row["wall_s"])
            top = max(cats, key=cats.get) if cats else "other"
            label = ("final" if row["epoch"] is None
                     else f"epoch {row['epoch']}")
            args = dict(cats)
            if row["residual_s"] is not None:
                args["residual_s"] = row["residual_s"]
            trace_events.append({
                "ph": "X", "cat": "goodput",
                "name": f"{label}: {top}", "pid": r,
                "tid": _TID_GOODPUT, "ts": start,
                "dur": round(row["wall_s"] * 1e6, 3), "args": args,
            })
            trace_events.append({
                "ph": "C", "cat": "goodput", "name": "goodput (s)",
                "pid": r, "tid": _TID_GOODPUT, "ts": start,
                "args": cats,
            })
    # Per-request track (serving tier, tracing.py): each request's span
    # chain laid out sequentially from its admission stamp — the chain
    # property (sum(spans) == total_s) means the slices tile exactly,
    # so queue_wait vs batch_form vs infer reads directly off the row.
    for rec in requests:
        r = int(rec["rank"])
        t = float(rec["mono_admit"])
        wall = (float(rec["ts_admit"])
                if isinstance(rec.get("ts_admit"), (int, float)) else None)
        spans = rec.get("spans", {})
        args = {k: rec[k] for k in ("id", "status", "outcome", "bucket",
                                    "latency_ms") if k in rec}
        for name in tracing.SPAN_ORDER:
            dur = spans.get(name)
            if not isinstance(dur, (int, float)) or dur < 0:
                continue
            trace_events.append({
                "ph": "X", "cat": "request", "name": name,
                "pid": r, "tid": _TID_REQUESTS,
                "ts": us(r, t, wall), "dur": round(float(dur) * 1e6, 3),
                "args": args,
            })
            t += float(dur)
    # Stable per-rank ordering: metadata first, then strictly by
    # (pid, ts) — Perfetto tolerates any order, humans and tests don't.
    trace_events.sort(key=lambda e: (e.get("pid", -1),
                                     0 if e["ph"] == "M" else 1,
                                     e.get("ts", -1.0)))

    skew = _skew_report(events)
    stragglers = _stragglers(events, dumps, ranks)
    rooflines = _roofline_summaries(events, rsl_path)
    trace = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "distributedpytorch_tpu timeline",
            "alignment": method,
            "ranks": ranks,
            "skew": skew,
            "stragglers": stragglers,
            "roofline": rooflines,
        },
    }
    return {"trace": trace, "skew": skew, "stragglers": stragglers,
            "ranks": ranks, "alignment": method, "warnings": warnings,
            "roofline": rooflines}


def _roofline_summaries(events: List[Dict[str, Any]], rsl_path: str
                        ) -> Dict[str, Any]:
    """Per-rank op-level blame for the timeline annotation: the newest
    ``roofline`` telemetry event per rank (roofline.py emits one after
    every analyzed capture), falling back to RSL_PATH/roofline.json —
    an offline `main.py roofline` run is rank-agnostic, keyed "*"."""
    out: Dict[str, Any] = {}
    for ev in events:
        if ev.get("kind") != "event" or ev.get("name") != "roofline":
            continue
        rank = ev.get("rank")
        if not isinstance(rank, int):
            continue
        prev = out.get(str(rank))
        if prev and prev.get("_mono", -1) >= ev.get("mono", 0):
            continue
        a = _attrs(ev)
        out[str(rank)] = {"coverage": a.get("coverage"),
                          "top_ops": a.get("top_ops"),
                          "source": "telemetry",
                          "_mono": ev.get("mono", 0)}
    for v in out.values():
        v.pop("_mono", None)
    if not out:
        try:
            with open(os.path.join(rsl_path, "roofline.json")) as f:
                rep = json.load(f)
            out["*"] = {
                "coverage": rep.get("coverage"),
                "top_ops": [{"name": r.get("name"),
                             "time_share": r.get("time_share"),
                             "bound": r.get("bound")}
                            for r in (rep.get("ops") or [])[:3]],
                "source": "roofline.json",
            }
        except (OSError, ValueError):
            pass
    return out


def render_summary(result: Dict[str, Any], out_path: str) -> str:
    """Human-readable digest printed by the CLI next to the trace file."""
    lines = [f"timeline: {len(result['ranks'])} rank(s), clock alignment "
             f"via {result['alignment']}",
             f"wrote {out_path} (load in https://ui.perfetto.dev)"]
    for w in result["warnings"]:
        lines.append(f"warning: {w}")
    skew = result["skew"]
    if skew["max_wall_skew_s"] is not None:
        lines.append(f"cross-rank wall-clock skew: "
                     f"max {skew['max_wall_skew_s'] * 1e3:.3f} ms")
        for e, v in skew["wall_skew_s_per_epoch"].items():
            lines.append(f"  boundary epoch {e}: {v * 1e3:.3f} ms")
    else:
        lines.append("cross-rank wall-clock skew: n/a "
                     "(fewer than 2 ranks at any health boundary)")
    lines.append("straggler attribution:")
    lines.append(f"  {'rank':>4s} {'epochs':>6s} {'mean_epoch_s':>12s} "
                 f"{'steps':>6s} {'mean_step_s':>12s} {'wait_share':>10s}")
    for row in result["stragglers"]:

        def _f(v, spec):
            return format(v, spec) if v is not None else "-"

        flag = "  <- straggler" if row.get("straggler") else ""
        lines.append(
            f"  {row['rank']:>4d} {row['epochs_seen']:>6d} "
            f"{_f(row['mean_epoch_s'], '>12.4f')} "
            f"{row['steps_recorded']:>6d} "
            f"{_f(row['mean_step_s'], '>12.5f')} "
            f"{_f(row['data_wait_share'], '>10.3f')}{flag}")
    rl = result.get("roofline") or {}
    if rl:
        lines.append("roofline attribution (per rank):")
        for rank in sorted(rl, key=lambda k: (k == "*", k)):
            info = rl[rank]
            tops = ", ".join(
                f"{t['name']} {t['time_share'] * 100:.0f}% "
                f"({t['bound']}-bound)"
                for t in (info.get("top_ops") or [])[:3]
                if t.get("time_share") is not None) or "-"
            cov = info.get("coverage")
            cov_s = f"{cov * 100:.1f}%" if cov is not None else "-"
            who = f"rank {rank}" if rank != "*" else "run"
            lines.append(f"  {who}: {cov_s} attributed; top: {tops} "
                         f"[{info.get('source')}]")
    return "\n".join(lines)


def write_timeline(rsl_path: str, out: Optional[str] = None
                   ) -> Tuple[str, Dict[str, Any]]:
    """Build + write the trace JSON; returns (path, build result)."""
    result = build_timeline(rsl_path)
    path = out or os.path.join(rsl_path, "timeline.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(result["trace"], f, default=float)
    os.replace(tmp, path)
    return path, result


def run_cli(rsl_path: str, out: Optional[str] = None) -> str:
    """CLI entry point: write the trace, return the printable summary."""
    path, result = write_timeline(rsl_path, out=out)
    return render_summary(result, path)
