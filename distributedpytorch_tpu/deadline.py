"""Deadline-bounded outbound HTTP — the one way this repo talks to a
socket it does not own (ISSUE 19 satellite).

Two failure shapes motivate the module:

  * a WEDGED peer (accepts the connection, never answers) must cost at
    most the per-call timeout, never an unbounded handler stall — so
    every helper here takes a mandatory ``timeout_s`` and graftlint
    rule 20 (``outbound-call-without-timeout``) rejects any raw
    urllib/socket/http.client call in the serving/fleet/controller
    modules that lacks one;
  * a CYCLE of many calls (the fleet collector scraping N exporters,
    the front door probing N replicas) must finish inside its caller's
    period even when several peers wedge at once — ``Deadline`` is the
    spend-down budget threaded through such a cycle: each call gets
    ``min(its own timeout, what's left of the budget)``, and a spent
    budget turns the remaining calls into immediate failures instead
    of queued stalls.

Clock contract (telemetry.py): budgets are ``time.monotonic``
differences — wall clock is never subtracted (graftlint rule 13).
All helpers swallow transport errors into ``None`` / status-0 returns:
the callers (collector age-out, front-door ejection) treat "no answer"
as data, not as an exception path.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple


class Deadline:
    """A spend-down time budget for a multi-call cycle.  Created at the
    top of the cycle; every outbound call bounds its own timeout by
    ``remaining()`` so the cycle as a whole cannot overrun the budget
    by more than one in-flight call."""

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self._t0 = time.monotonic()

    def remaining(self) -> float:
        return max(0.0, self.budget_s - (time.monotonic() - self._t0))

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def bound(self, timeout_s: float) -> float:
        """The effective timeout for the next call: the caller's own
        cap or what is left of the budget, whichever is smaller."""
        return min(float(timeout_s), self.remaining())


def fetch(url: str, timeout_s: float,
          deadline: Optional[Deadline] = None) -> Optional[str]:
    """GET ``url`` with a hard timeout; the body as text, or None on
    any transport/HTTP/parse failure — including a deadline already
    spent, which costs zero wall clock."""
    t = float(timeout_s) if deadline is None else deadline.bound(timeout_s)
    if t <= 0.0:
        return None
    try:
        with urllib.request.urlopen(url, timeout=t) as resp:
            return resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None


def fetch_json(url: str, timeout_s: float,
               deadline: Optional[Deadline] = None
               ) -> Optional[Dict[str, Any]]:
    """GET ``url`` and parse the body as a JSON object; None on any
    failure (transport, budget, or a body that is not a dict)."""
    body = fetch(url, timeout_s, deadline=deadline)
    if body is None:
        return None
    try:
        doc = json.loads(body)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def post_json(url: str, doc: Dict[str, Any], timeout_s: float
              ) -> Tuple[int, Dict[str, Any]]:
    """POST ``doc`` as JSON with a hard timeout.  Returns
    ``(status, body_dict)``; HTTP error statuses are returned (not
    raised) with their parsed body, transport failures return
    ``(0, {})`` — callers branch on status, never on exceptions."""
    data = json.dumps(doc).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=float(timeout_s)) as r:
            return int(r.status), _body_dict(r.read())
    except urllib.error.HTTPError as e:
        try:
            raw = e.read()
        except OSError:
            raw = b""
        return int(e.code), _body_dict(raw)
    except (urllib.error.URLError, OSError, ValueError):
        return 0, {}


def _body_dict(raw: bytes) -> Dict[str, Any]:
    try:
        doc = json.loads(raw.decode("utf-8", "replace") or "{}")
    except ValueError:
        return {}
    return doc if isinstance(doc, dict) else {}
