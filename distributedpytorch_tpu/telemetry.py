"""L1: structured telemetry — per-rank JSONL metrics, spans, and reports.

The reference's only observability is unstructured log lines (ref
classif.py:171-178) and the reproduction was barely better: throughput/MFU
numbers existed only inside bench.py, and the one jax.profiler trace
(--profile) had nothing machine-readable to line up against.  This module
is the missing layer: a process-local metrics registry plus a ``span``
context manager that emit machine-readable JSONL events to
``RSL_PATH/telemetry/rank<process_index>.jsonl`` — one file per process,
no cross-host coordination, so multi-host runs get straggler visibility
by simply aggregating the files afterwards (``aggregate``/``render_report``
below, surfaced as the ``telemetry`` CLI subcommand).

Zero-cost when disabled: ``get()`` returns a module-level singleton that
is a no-op ``Telemetry(enabled=False)`` until ``configure()`` swaps in an
enabled one; hot paths guard their instrumentation on ``tel.enabled`` so
the off state adds no per-step work (acceptance criterion).  Events are
buffered and flushed at epoch/close boundaries — the hot loop never does
file I/O.

Timestamp contract (every line carries all three):

  ``ts``    wall clock (``time.time()``, epoch seconds) — for humans and
            for cross-host correlation ONLY; hosts' wall clocks skew and
            step, so nothing may be ordered by it.
  ``mono``  monotonic clock (``time.monotonic()``, arbitrary per-process
            origin) — the ordering clock.  Within one rank file ``mono``
            is non-decreasing in real time; the timeline merger orders
            and aligns ranks on ``mono`` (offset-corrected at health-
            allgather boundaries) and never trusts ``ts`` for ordering.
  ``rank``  global process index.

Span durations (``dur_s``) are measured with ``perf_counter`` and are
independent of both stamps; both stamps are taken at *emit* time, which
for spans is span END (start = stamp - dur_s).

Event schema (one JSON object per line):

  kind="span"       name, dur_s, parent (enclosing span name or null),
                    attrs (span-specific: epoch, step count, path, ...)
  kind="counter"    name, value       (monotonic total, emitted at flush)
  kind="gauge"      name, value, attrs (emitted on every set)
  kind="histogram"  name, count, sum, min, max, mean, p50, p90, p95, p99
                    (summary, emitted at flush)
  kind="event"      name, attrs       (point events: preemption, meta)

Span names used by the framework (the report groups on these):
  epoch, train_pass, eval_pass, train_dispatch, train_step, eval_step,
  chunk_dispatch, ckpt_save, ckpt_restore, ckpt_save_blocking,
  ckpt_save_background.
Counter/gauge names:
  data/wait_s (steady-state consumer blocking), data/warmup_s (prefetch
  initial fill, before the first batch was requested), data/batches,
  data/starved_steps, data/queue_depth_sum,
  throughput/samples_per_sec_per_chip, throughput/mfu,
  compile/warmup_s, compile/cache_hit (--aot-warmup + the persistent
  compilation cache, runtime.py).

Thread-safety: the emit path is locked, and the span stack is
THREAD-LOCAL — background workers (the async checkpoint writer, the
pipeline producer threads) can open spans without corrupting the driver
thread's parent chain.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

_FLUSH_EVERY = 1024  # buffered events before an automatic flush


# -- record schema factories ------------------------------------------
#
# The JSONL line shape is a CONTRACT shared by the live emitter below
# and the fleet simulator (sim/artifacts.py), which writes the same
# schema with virtual clocks.  Both go through these two functions so
# the schema cannot fork: a field added to the live stream is a field
# the simulated stream gets for free, and vice versa.

def stamp_record(payload: Dict[str, Any], *, ts: float, mono: float,
                 rank: int) -> Dict[str, Any]:
    """One telemetry record: the caller's payload plus the paired
    ``ts``/``mono`` stamps and the emitting rank.  Pure — the clocks
    are arguments, so the simulator stamps virtual time through the
    exact code path the live emitter uses."""
    out = dict(payload)
    out["ts"] = ts
    out["mono"] = mono
    out["rank"] = rank
    return out


def encode_line(payload: Dict[str, Any]) -> str:
    """The canonical JSONL serialization (sorted keys, floats for
    anything exotic) — byte-stable for identical payloads, which is
    what makes same-seed simulator runs byte-identical."""
    return json.dumps(payload, sort_keys=True, default=float)


class Counter:
    """Monotonic accumulator; summarized as one event at flush time."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def add(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    """Last-value metric; every ``set`` emits an event (time series)."""

    __slots__ = ("name", "value", "_tel")

    def __init__(self, name: str, tel: "Telemetry"):
        self.name = name
        self.value: Optional[float] = None
        self._tel = tel

    def set(self, value: Optional[float], **attrs: Any) -> None:
        """``None`` is a recorded null — the event documents the gauge
        was considered but unavailable (e.g. MFU on an unknown chip)."""
        self.value = None if value is None else float(value)
        self._tel._emit({"kind": "gauge", "name": self.name,
                         "value": self.value,
                         **({"attrs": attrs} if attrs else {})})


class Histogram:
    """Streaming timing histogram: log-bucketed quantile sketch with
    exact count/sum/min/max, summarized at flush with p50/p90/p95/p99.

    The previous implementation kept the FIRST 4096 raw samples, so on
    long runs the quantiles described the warmup, not the run — and
    they only existed at close time.  The sketch keeps one counter per
    geometric bucket (2% growth => <=1% representative error, far under
    the report's precision), is O(1) per observe with bounded memory
    regardless of run length, covers every observation, and is
    queryable at any moment — ``quantile()`` backs the live ``/metrics``
    exporter (goodput.py) as well as the close-time summary event.
    """

    __slots__ = ("name", "count", "sum", "min", "max", "_buckets",
                 "_nonpos")

    _GROWTH_LOG = math.log(1.02)  # bucket boundaries grow 2% per index

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._buckets: Dict[int, int] = {}
        self._nonpos = 0  # observations <= 0 (durations shouldn't, but)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if value <= 0.0:
            self._nonpos += 1
            return
        idx = math.floor(math.log(value) / self._GROWTH_LOG)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def quantile(self, q: float) -> float:
        """Streaming quantile (bucket geometric midpoint, clamped to the
        exact observed range).  Safe to call from a scrape thread while
        the driver observes: the snapshot below is a single C-level op."""
        if not self.count:
            return 0.0
        target = min(self.count - 1, int(q * self.count))
        cum = self._nonpos
        if cum > target:
            return self.min
        for idx, n in sorted(self._buckets.items()):
            cum += n
            if cum > target:
                rep = math.exp((idx + 0.5) * self._GROWTH_LOG)
                return min(self.max, max(self.min, rep))
        return self.max

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"count": self.count, "sum": self.sum}
        if not self.count:
            return out
        out.update(min=self.min, max=self.max, mean=self.sum / self.count)
        for q, label in ((0.5, "p50"), (0.9, "p90"), (0.95, "p95"),
                         (0.99, "p99")):
            out[label] = self.quantile(q)
        return out

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another sketch into this one, in place.  Bucket-wise
        addition is EXACT for the sketch: both sides bucket values by
        the same geometric boundaries, so the merged sketch is
        identical to one that observed both streams directly — the
        merged quantile carries the same <=1% representative error as
        a single-rank sketch, never more.  This is what makes fleet
        p95s possible at all: raw per-rank quantiles don't merge, the
        sketches they came from do (fleet.py's core primitive)."""
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self._nonpos += other._nonpos
        for idx, n in other._buckets.items():
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        return self

    @classmethod
    def from_parts(cls, name: str, count: int, total: float,
                   lo: float, hi: float, buckets: Dict[int, int],
                   nonpos: int = 0) -> "Histogram":
        """Rebuild a sketch from its serialized state (the fleet
        collector reconstructs per-rank sketches from the Prometheus
        ``_bucket{le=...}`` exposition, then merge()s them)."""
        h = cls(name)
        h.count = int(count)
        h.sum = float(total)
        h.min = float(lo) if count else math.inf
        h.max = float(hi) if count else -math.inf
        h._nonpos = int(nonpos)
        h._buckets = {int(k): int(v) for k, v in buckets.items()
                      if int(v) > 0}
        return h


class _Span:
    """Context manager recording one timed span; nests via a per-instance
    stack so the event carries its parent's name."""

    __slots__ = ("_tel", "name", "attrs", "_start", "_parent")

    def __init__(self, tel: "Telemetry", name: str, attrs: Dict[str, Any]):
        self._tel = tel
        self.name = name
        self.attrs = attrs
        self._start = 0.0
        self._parent: Optional[str] = None

    def __enter__(self) -> "_Span":
        stack = self._tel._span_stack
        self._parent = stack[-1] if stack else None
        stack.append(self.name)
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._start
        stack = self._tel._span_stack
        if stack and stack[-1] == self.name:
            stack.pop()
        self._tel._emit({"kind": "span", "name": self.name,
                         "dur_s": dur, "parent": self._parent,
                         **({"attrs": self.attrs} if self.attrs else {})})
        return False


class _NullSpan:
    """The disabled span: nothing measured, nothing emitted."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Telemetry:
    """Process-local registry + JSONL sink.

    One instance per process; the file is ``telemetry/rank<N>.jsonl``
    under the run's RSL_PATH.  Disabled instances never touch the
    filesystem: every method is a cheap no-op.
    """

    def __init__(self, enabled: bool = False, rsl_path: str = ".",
                 rank: int = 0):
        self.enabled = enabled
        self.rank = rank
        self._dir = os.path.join(rsl_path, "telemetry")
        self._path = os.path.join(self._dir, f"rank{rank}.jsonl")
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._local = threading.local()
        self._buffer: List[str] = []
        # REENTRANT on purpose: the preempt signal handler
        # (utils.GracefulShutdown) calls event() on the main thread and
        # may interrupt a frame that already holds this lock — a plain
        # Lock self-deadlocks there, hanging the run the handler exists
        # to stop cleanly.
        self._lock = threading.RLock()
        self._file = None
        self.write_errors = 0
        self._sink_dead = False

    @property
    def _span_stack(self) -> List[str]:
        # Per-thread: a span opened by a background writer must not become
        # the parent of (or pop) the driver thread's spans.
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- registry -----------------------------------------------------

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, self)
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram(name)
        return h

    def metrics_snapshot(self):
        """Stable views of the live registries for out-of-band readers
        (the /metrics exporter's scrape threads).  The list() copies are
        single C-level operations — atomic under the GIL even while the
        driver thread is registering new metrics."""
        return (list(self._counters.values()), list(self._gauges.values()),
                list(self._histograms.values()))

    def span(self, name: str, **attrs: Any):
        """Timed context manager; emits a span event on exit.  The
        disabled instance returns a shared no-op (no clock reads)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def event(self, name: str, **attrs: Any) -> None:
        """Point event (preemption, run metadata, ...)."""
        self._emit({"kind": "event", "name": name,
                    **({"attrs": attrs} if attrs else {})})

    # -- sink ---------------------------------------------------------

    def _emit(self, payload: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        # Paired stamps — see the module-docstring timestamp contract.
        line = encode_line(stamp_record(payload, ts=time.time(),
                                        mono=time.monotonic(),
                                        rank=self.rank))
        with self._lock:
            self._buffer.append(line)
            if len(self._buffer) >= _FLUSH_EVERY:
                self._write_locked()

    def _write_locked(self) -> None:
        if not self._buffer:
            return
        if self._sink_dead:
            # An earlier write failed: telemetry is observability, not
            # training state — drop events rather than retry a dead disk
            # on every flush (the report shows the write_errors count).
            self._buffer.clear()
            return
        try:
            from . import faults

            faults.fire("telemetry.write")
            if self._file is None:
                os.makedirs(self._dir, exist_ok=True)
                self._file = open(self._path, "a", encoding="utf-8")
            self._file.write("\n".join(self._buffer) + "\n")
            self._file.flush()
        except OSError as e:
            # A full/unwritable disk must NEVER kill training (ISSUE 5
            # satellite): count it, disable this rank's sink, train on.
            self.write_errors += 1
            self._sink_dead = True
            logging.error(
                f"telemetry: cannot write {self._path!r} ({e}); "
                f"disabling further telemetry writes for rank "
                f"{self.rank} — training continues")
        self._buffer.clear()

    def flush(self) -> None:
        """Write buffered events to disk (epoch boundaries; cheap when
        nothing is pending)."""
        if not self.enabled:
            return
        with self._lock:
            self._write_locked()

    def close(self) -> None:
        """Emit counter/histogram summaries, flush, close the file.
        Idempotent: the instance is disabled afterwards, so a second
        close (or a late emit) is a no-op rather than a duplicate
        summary block."""
        if not self.enabled:
            return
        if self.write_errors:
            self.counter("telemetry/write_errors").add(self.write_errors)
            # One last attempt for the summaries below: the condition
            # (disk full, quota) may have cleared since the failure, and
            # the write_errors counter is how the report learns events
            # were dropped.  Failing again just re-kills the sink.
            self._sink_dead = False
        for c in self._counters.values():
            self._emit({"kind": "counter", "name": c.name,
                        "value": c.value})
        for h in self._histograms.values():
            self._emit({"kind": "histogram", "name": h.name, **h.summary()})
        with self._lock:
            self._write_locked()
            if self._file is not None:
                self._file.close()
                self._file = None
        self.enabled = False


_active = Telemetry(enabled=False)


def get() -> Telemetry:
    """The process's active telemetry (a disabled no-op by default)."""
    return _active


def configure(rsl_path: str, enabled: bool, rank: Optional[int] = None
              ) -> Telemetry:
    """Install the process's telemetry instance (drivers call this once,
    after runtime init so the rank is the GLOBAL process index).  A
    previous enabled instance is closed first — re-invocation safe, same
    convention as utils.initialize_logging."""
    global _active
    if _active.enabled:
        _active.close()
    if rank is None:
        try:
            import jax

            rank = jax.process_index()
        except Exception:  # no jax / backend not initialized: rank 0
            rank = 0
    _active = Telemetry(enabled=enabled, rsl_path=rsl_path, rank=rank)
    return _active


# -- report: aggregate per-rank JSONL into a human-readable summary ----


def load_events(telemetry_dir: str) -> List[Dict[str, Any]]:
    """All events from every ``rank*.jsonl`` under ``telemetry_dir``.
    Lines that fail to parse are skipped (a run killed mid-write leaves
    at most one torn last line per file)."""
    events: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError as e:
        raise ValueError(
            f"no telemetry directory at {telemetry_dir!r} "
            f"({e.strerror or e}); run with --telemetry first") from e
    for fn in names:
        if not (fn.startswith("rank") and fn.endswith(".jsonl")):
            continue
        with open(os.path.join(telemetry_dir, fn), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    events.append(ev)
    if not events:
        raise ValueError(f"no telemetry events under {telemetry_dir!r}")
    return events


def aggregate(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-rank aggregation: span stats by name, per-rank epoch means
    (straggler view), counter totals, latest gauges, starvation fraction.
    Pure data-in/data-out so tests (and notebooks) can assert on it."""
    spans: Dict[str, Dict[str, Any]] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, Dict[int, float]] = {}
    histograms: Dict[str, List[Dict[str, Any]]] = {}
    point_events: List[Dict[str, Any]] = []
    rank_epoch: Dict[int, List[float]] = {}
    ranks = set()
    skipped = 0
    for ev in events:
        # A rank file can be torn mid-write or hand-edited: an event
        # with a missing name or a non-numeric value must degrade to a
        # skipped line, never crash the whole report.
        try:
            rank = int(ev.get("rank", 0))
            kind, name = ev.get("kind"), ev.get("name")
            if not isinstance(name, str):
                skipped += 1
                continue
            if kind == "span":
                dur = float(ev.get("dur_s", 0.0))
                s = spans.setdefault(name, {"count": 0, "total_s": 0.0,
                                            "max_s": 0.0})
                s["count"] += 1
                s["total_s"] += dur
                s["max_s"] = max(s["max_s"], dur)
                if name == "epoch":
                    rank_epoch.setdefault(rank, []).append(dur)
            elif kind == "counter":
                counters[name] = counters.get(name, 0.0) \
                    + float(ev.get("value", 0.0))
            elif kind == "gauge":
                if ev.get("value") is not None:  # null = unavailable
                    gauges.setdefault(name, {})[rank] = float(ev["value"])
            elif kind == "histogram":
                histograms.setdefault(name, []).append(ev)
            elif kind == "event":
                point_events.append(ev)
            else:
                skipped += 1
                continue
            ranks.add(rank)
        except (TypeError, ValueError):
            skipped += 1
            continue
    for s in spans.values():
        s["mean_s"] = s["total_s"] / max(s["count"], 1)

    # Data-starvation fraction: host time blocked waiting on batches as a
    # share of the train passes it stalled (both from the same rank set).
    train_total = (spans.get("train_pass", {}).get("total_s", 0.0)
                   or spans.get("train_dispatch", {}).get("total_s", 0.0))
    wait = counters.get("data/wait_s", 0.0)
    starvation = wait / train_total if train_total > 0 else None

    return {
        "ranks": sorted(ranks),
        "skipped_events": skipped,
        "spans": spans,
        "counters": counters,
        "gauges": {name: {"latest_per_rank": per,
                          "mean": sum(per.values()) / len(per)}
                   for name, per in gauges.items()},
        "histograms": histograms,
        "events": point_events,
        "epoch_s_per_rank": {r: sum(v) / len(v)
                             for r, v in rank_epoch.items()},
        "data_starvation_fraction": starvation,
    }


def render_report(agg: Dict[str, Any]) -> str:
    """The human-readable summary the ``telemetry`` subcommand prints."""
    lines = []
    lines.append(f"telemetry report — {len(agg['ranks'])} rank(s): "
                 f"{agg['ranks']}")
    if agg.get("skipped_events"):
        lines.append(f"({agg['skipped_events']} malformed event(s) "
                     f"skipped)")
    # Writer-failure visibility (ISSUE 5 satellite): a rank whose JSONL
    # sink died mid-run reports a write_errors counter if its final
    # close-time write landed — and if it didn't, the rank is simply
    # missing from the files, which the run_start processes attr exposes.
    werr = agg["counters"].get("telemetry/write_errors")
    if werr:
        lines.append(f"WARNING: {int(werr)} telemetry write error(s) — "
                     f"some events were dropped (see run log)")
    expected = max((int(e.get("attrs", {}).get("processes", 0))
                    for e in agg["events"]
                    if e.get("name") == "run_start"), default=0)
    # A mid-run joiner announces itself with elastic/join: its stream
    # starting late (or reusing a departed rank's file) is by design.
    joined = sorted({int(e["attrs"]["new_rank"]) for e in agg["events"]
                     if e.get("name") == "elastic/join"
                     and isinstance(e.get("attrs"), dict)
                     and isinstance(e["attrs"].get("new_rank"), int)})
    if joined:
        lines.append(f"note: rank(s) {joined} joined mid-run in an "
                     f"elastic grow; their streams starting late is "
                     f"expected")
    if expected > len(agg["ranks"]):
        missing = sorted(set(range(expected)) - set(agg["ranks"]))
        # An elastic run changes membership by design: every member
        # emits an elastic/reconfigure (and a joiner an elastic/join)
        # event carrying its generation's new_world.  The current
        # world is the NEWEST generation's size — not the minimum over
        # the run, which a shrink-then-grow history would underread,
        # mislabeling readmitted rank slots as departed.  Missing rank
        # slots at/above the current world departed in a reconfigure —
        # a note, not a writer failure; anything below it really is a
        # lost/disabled writer.
        gens: Dict[int, int] = {}
        for e in agg["events"]:
            if e.get("name") not in ("elastic/reconfigure",
                                     "elastic/join"):
                continue
            attrs = e.get("attrs")
            if not isinstance(attrs, dict):
                continue
            g, w = attrs.get("generation"), attrs.get("new_world")
            if isinstance(g, int) and isinstance(w, int):
                gens[g] = w
        final_world = gens[max(gens)] if gens else expected
        departed = [r for r in missing if r >= final_world]
        missing = [r for r in missing if r < final_world]
        if departed:
            lines.append(f"note: rank(s) {departed} departed in an "
                         f"elastic reconfigure (world now "
                         f"{final_world}); their files ending early — "
                         f"or never landing — is expected, not loss")
        if missing:
            lines.append(f"WARNING: {expected} process(es) ran but only "
                         f"{len(agg['ranks'])} rank file(s) readable — "
                         f"rank(s) {missing} skipped (telemetry writer "
                         f"disabled or file lost)")

    spans = agg["spans"]
    if spans:
        lines.append("")
        lines.append("slowest spans (by total time):")
        lines.append(f"  {'span':<16} {'count':>6} {'total_s':>10} "
                     f"{'mean_s':>10} {'max_s':>10}")
        for name, s in sorted(spans.items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {name:<16} {s['count']:>6} "
                         f"{s['total_s']:>10.3f} {s['mean_s']:>10.3f} "
                         f"{s['max_s']:>10.3f}")

    hists = agg["histograms"]
    if hists:
        lines.append("")
        lines.append("hot-path duration percentiles (per-step histograms; "
                     "count-weighted across ranks):")
        lines.append(f"  {'histogram':<20} {'count':>8} {'p50':>10} "
                     f"{'p95':>10} {'p99':>10} {'max':>10}")
        for name in sorted(hists):
            summaries = [h for h in hists[name] if h.get("count")]
            if not summaries:
                continue
            n = sum(int(h["count"]) for h in summaries)

            def _wq(label, summaries=summaries, n=n):
                # Exact per-rank quantiles don't merge; the count-weighted
                # mean is the documented approximation (single-rank runs —
                # the common case — are exact).  Live sketches DO merge
                # (Histogram.merge, the fleet collector's path) but the
                # JSONL summary events here carry only the quantiles, not
                # the buckets, so the report keeps the approximation.
                vals = [(float(h.get(label, 0.0)), int(h["count"]))
                        for h in summaries if label in h]
                if not vals:
                    return 0.0
                return sum(v * c for v, c in vals) / sum(c for _, c in vals)

            mx = max(float(h.get("max", 0.0)) for h in summaries)
            lines.append(f"  {name:<20} {n:>8} {_wq('p50'):>10.4f} "
                         f"{_wq('p95'):>10.4f} {_wq('p99'):>10.4f} "
                         f"{mx:>10.4f}")

    per_rank = agg["epoch_s_per_rank"]
    if len(per_rank) > 1:
        slowest = max(per_rank, key=per_rank.get)
        fastest = min(per_rank, key=per_rank.get)
        lines.append("")
        lines.append("stragglers (mean epoch seconds per rank):")
        for r in sorted(per_rank):
            tag = (" <- slowest" if r == slowest else
                   " <- fastest" if r == fastest else "")
            lines.append(f"  rank {r}: {per_rank[r]:.3f}s{tag}")

    frac = agg["data_starvation_fraction"]
    if frac is not None:
        lines.append("")
        lines.append(f"data starvation: {frac * 100:.1f}% of train time "
                     f"spent waiting on batches "
                     f"({agg['counters'].get('data/wait_s', 0.0):.3f}s)")
    starved = agg["counters"].get("data/starved_steps")
    batches = agg["counters"].get("data/batches")
    if starved is not None and batches:
        lines.append(f"prefetch: {int(starved)}/{int(batches)} steps found "
                     f"the queue empty")
    warm = agg["counters"].get("data/warmup_s")
    if warm is not None:
        lines.append(f"prefetch warmup (initial fill): {warm:.3f}s "
                     f"(excluded from wait_s)")

    gauges = agg["gauges"]
    tput = gauges.get("throughput/samples_per_sec_per_chip")
    if tput:
        lines.append("")
        lines.append(f"throughput: {tput['mean']:,.0f} samples/s/chip "
                     f"(latest per rank: "
                     f"{ {r: round(v, 1) for r, v in sorted(tput['latest_per_rank'].items()) } })")
    mfu = gauges.get("throughput/mfu")
    if mfu:
        lines.append(f"MFU: {mfu['mean'] * 100:.1f}%")

    warmup = gauges.get("compile/warmup_s")
    if warmup:
        hit = gauges.get("compile/cache_hit", {}).get("mean")
        lines.append(f"compile warmup: {warmup['mean']:.3f}s"
                     + (f" (persistent-cache hit: "
                        f"{'yes' if hit else 'no'})"
                        if hit is not None else ""))

    ckpt = {n: s for n, s in spans.items()
            if n in ("ckpt_save", "ckpt_restore", "ckpt_save_blocking",
                     "ckpt_save_background")}
    for name, s in sorted(ckpt.items()):
        lines.append(f"{name}: {s['count']}x, total {s['total_s']:.3f}s, "
                     f"mean {s['mean_s']:.3f}s")
    blocking = spans.get("ckpt_save_blocking")
    background = spans.get("ckpt_save_background")
    if blocking and background:
        total = blocking["total_s"] + background["total_s"]
        if total > 0:
            lines.append(
                f"async checkpointing: {blocking['total_s']:.3f}s of "
                f"{total:.3f}s save time on the critical path "
                f"({blocking['total_s'] / total * 100:.1f}%)")

    # Serving saturation (ISSUE 15): the tier's one-look health — how
    # much load arrived, how much was shed at the bounded queue (the
    # saturation fraction), and how well the micro-batcher filled its
    # buckets (padding is paid compute).  The latency percentiles are
    # already in the histogram table above (serve/request_latency_ms).
    requests = agg["counters"].get("serve/requests")
    if requests:
        shed = agg["counters"].get("serve/shed", 0.0)
        answered = agg["counters"].get("serve/answered", 0.0)
        failed = agg["counters"].get("serve/failed", 0.0)
        lines.append("")
        lines.append(f"serving: {int(requests)} requests — "
                     f"{int(answered)} answered, {int(failed)} failed, "
                     f"{int(shed)} shed at the full queue "
                     f"(saturation {shed / requests * 100:.1f}%)")
        sbatches = agg["counters"].get("serve/batches")
        rows = agg["counters"].get("serve/batch_rows", 0.0)
        padded = agg["counters"].get("serve/padded_rows", 0.0)
        if sbatches and rows:
            lines.append(
                f"  micro-batches: {int(sbatches)} dispatched, mean "
                f"fill {(rows - padded) / sbatches:.1f} rows, padding "
                f"overhead {padded / rows * 100:.1f}% of batch rows")

    preempts = [e for e in agg["events"] if e.get("name") == "preempt"]
    if preempts:
        lines.append(f"preemption events: {len(preempts)}")
    return "\n".join(lines)


def report(rsl_path: str) -> str:
    """Load + aggregate + render for a run directory (CLI entry)."""
    return render_report(aggregate(load_events(
        os.path.join(rsl_path, "telemetry"))))


def json_report(rsl_path: str) -> str:
    """The same aggregate render_report formats, as JSON — the
    machine-readable face gate scripts and bench_trend consume instead
    of scraping the human text (ISSUE 12 satellite)."""
    agg = aggregate(load_events(os.path.join(rsl_path, "telemetry")))
    return json.dumps(agg, indent=2, sort_keys=True, default=float)
