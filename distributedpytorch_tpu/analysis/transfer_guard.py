"""Transfer-guard sanitizer: a 1-epoch CPU smoke that FAILS on any
unsanctioned device->host sync.

The static pass (rules.HostSyncInStepLoop) catches the syntactic shapes
of the paper's per-batch ``.item()`` bug; this leg catches what AST
cannot see — a sync hidden behind a helper, a library call that
materializes a device value, an f-string formatting a jax.Array.

Two guard layers run during the smoke epoch:

  * ``jax.transfer_guard_device_to_host("disallow_explicit")`` — jax's
    native guard.  On TPU/GPU it rejects every implicit AND explicit
    device->host transfer; on the CPU backend it is VACUOUS (a CPU
    buffer is already host memory, so jax records no transfer — probed
    and pinned in tests/test_transfer_guard.py).
  * the sanitizer's own patched sync primitives —
    ``jax.device_get``, ``Array.item/__float__/__int__/__index__/
    __bool__`` raise :class:`HostTransferViolation` unless the calling
    thread is inside ``runtime.sanctioned_host_transfer()``.  This is
    what makes the smoke sharp on the CPU backend the gate runs on.

The framework's few legitimate per-epoch sync points (epoch-end metric
fetches, checkpoint snapshots) wrap themselves in
``runtime.sanctioned_host_transfer()``, so a clean epoch passes — and
any OTHER sync fails the smoke instead of silently serializing the hot
path.  Proven sharp in tests/test_transfer_guard.py: injecting a
deliberate per-step ``jax.device_get`` into the train loop flips the
result.

Run it:  python scripts/graftlint.py --smoke   (gate.sh leg;
JAX_PLATFORMS=cpu is forced so it never needs hardware).
"""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile
from typing import Optional


class HostTransferViolation(RuntimeError):
    """An unsanctioned device->host sync during the guarded smoke."""


def _check_sanctioned(what: str) -> None:
    from .. import runtime

    if not runtime.host_transfer_sanctioned():
        raise HostTransferViolation(
            f"unsanctioned device->host sync via {what} — per-step host "
            f"syncs serialize the driver against every dispatch; "
            f"accumulate on device and sync per epoch (or wrap a "
            f"legitimate per-epoch sync in "
            f"runtime.sanctioned_host_transfer())")


@contextlib.contextmanager
def _patched_sync_primitives():
    """Patch the Python-level sync primitives to consult the sanction
    marker.  jax.device_get and the ArrayImpl scalar-conversion methods
    are plain Python attributes (verified on jax 0.4.x); everything is
    restored on exit, so the patch cannot leak into other tests."""
    import jax
    from jax._src.array import ArrayImpl

    orig_get = jax.device_get

    def guarded_device_get(*args, **kwargs):
        _check_sanctioned("jax.device_get")
        return orig_get(*args, **kwargs)

    method_names = ("item", "__float__", "__int__", "__index__",
                    "__bool__")
    originals = {}
    for name in method_names:
        fn = ArrayImpl.__dict__.get(name)
        if fn is None:
            continue

        def make(name, fn):
            def guarded(self, *a, **k):
                _check_sanctioned(f"Array.{name}")
                return fn(self, *a, **k)
            return guarded

        originals[name] = fn
        setattr(ArrayImpl, name, make(name, fn))
    jax.device_get = guarded_device_get
    try:
        yield
    finally:
        jax.device_get = orig_get
        for name, fn in originals.items():
            setattr(ArrayImpl, name, fn)


def _smoke_config(rsl_path: str):
    from ..config import Config

    # Streaming mode on the debug-subset synthetic corpus: the per-step
    # driver loop — exactly the code path the paper's bug class lives in
    # — with a real checkpoint write at the epoch boundary.
    return Config(action="train", data_path="/tmp/nodata",
                  rsl_path=rsl_path, dataset="synthetic",
                  model_name="mlp", batch_size=8, nb_epochs=1,
                  debug=True, half_precision=False, data_mode="stream",
                  prefetch=2, producer_threads=0, no_compile_cache=True)


def run_smoke(rsl_path: Optional[str] = None,
              inject_host_sync: bool = False) -> bool:
    """One guarded smoke epoch.  Returns True when the epoch completed
    with no unsanctioned device->host transfer.

    ``inject_host_sync=True`` wraps the engine's train step so every
    step fetches its metrics to host — the reference's per-batch
    ``.item()`` bug, mechanically reproduced — and must flip the result
    to False (pinned in tests/test_transfer_guard.py).
    """
    import jax

    from .. import cli

    guard = getattr(jax, "transfer_guard_device_to_host", None)
    if guard is None:  # very old jax: the patched primitives still guard
        def guard(_level):
            return contextlib.nullcontext()

    tmp = None
    if rsl_path is None:
        tmp = tempfile.TemporaryDirectory(prefix="graftlint_smoke_")
        rsl_path = tmp.name
    cfg = _smoke_config(rsl_path)

    orig_build = cli._build_engine

    def build_and_inject(*args, **kwargs):
        engine = orig_build(*args, **kwargs)
        orig_step = engine.train_step

        def leaky_step(*step_args):
            out = orig_step(*step_args)
            jax.device_get(out[1])  # the deliberate per-step host sync
            return out

        engine.train_step = leaky_step
        return engine

    try:
        if inject_host_sync:
            cli._build_engine = build_and_inject
        with guard("disallow_explicit"), _patched_sync_primitives():
            result = cli.run_train(cfg)
    except Exception as e:
        # Any failure under the guard is a finding: either a disallowed
        # transfer (the point of the smoke) or a broken smoke config —
        # both must turn the gate red, with the cause printed.
        logging.error(f"transfer-guard smoke FAILED: {type(e).__name__}: "
                      f"{e}")
        return False
    finally:
        cli._build_engine = orig_build
        if tmp is not None:
            tmp.cleanup()
    if len(result["history"]) != 1:
        logging.error("transfer-guard smoke: run produced no epoch "
                      "history — smoke did not actually train")
        return False
    return True


def main() -> int:
    """CLI entry (scripts/graftlint.py --smoke).  Forces the CPU
    backend: the smoke is a correctness sanitizer, not a benchmark."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    ok = run_smoke()
    print("transfer-guard smoke: "
          + ("PASS (no unsanctioned device->host transfer in a "
             "streaming epoch)" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
