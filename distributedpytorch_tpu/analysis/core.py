"""graftlint core: findings, the ``# graftlint:`` pragma grammar, project
loading, and report rendering.

Pragma grammar (parsed with ``tokenize`` so strings never false-match):

  # graftlint: disable=<rule>[,<rule>...] -- <rationale>
      Suppress the named rule(s) on this line (trailing comment) or on
      the next code line (standalone comment line).  The rationale text
      after ``--`` is REQUIRED: a suppression that does not say why is
      itself reported (rule ``bad-suppression``).

  # graftlint: guarded-by=<sync-object> [-- rationale]
      Declares that the attribute assigned on this line is protected by
      the named synchronization object/protocol (a lock attribute, or a
      happens-before edge like ``_queue.join``).  Consumed by the
      ``thread-shared-state`` rule.

Exit contract (CLI): 0 = no findings, 1 = findings, 2 = usage error.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import subprocess
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


# -- shared AST helpers (used by rules.py and wholeprogram.py) ---------

def dotted(node: ast.AST) -> str:
    """Best-effort dotted name for a call target / reference:
    ``jax.lax.psum`` -> "jax.lax.psum", ``self._apply`` -> "self._apply",
    anything unresolvable -> ""."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif not parts:
        return ""
    return ".".join(reversed(parts))


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def last_seg(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def root_seg(name: str) -> str:
    return name.split(".", 1)[0] if name else ""


def kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)


class ModuleIndex:
    """One-pass node index shared by every rule and the whole-program
    build, so 19 rules don't each re-walk (and re-resolve dotted names
    over) the same trees.  Built lazily on first access, cached on the
    Module for the lifetime of the lint invocation.

    ``scopes`` maps each function (plus the module tree itself) to the
    nodes whose NEAREST enclosing function it is — nested function
    bodies belong to the nested function's scope, matching the
    scope-local taint rules (wall-clock, mixed-precision)."""

    def __init__(self, tree: ast.AST):
        self.nodes: List[ast.AST] = []
        self.calls: List[Tuple[ast.Call, str]] = []
        self.functions: List[ast.AST] = []
        self.classes: List[ast.ClassDef] = []
        self.scopes: List[Tuple[ast.AST, List[ast.AST]]] = []
        self.enclosing: Dict[int, ast.AST] = {}  # id(node) -> function
        scope_nodes: Dict[int, List[ast.AST]] = {id(tree): []}
        scope_of: Dict[int, ast.AST] = {id(tree): tree}
        stack: List[Tuple[ast.AST, ast.AST]] = [
            (child, tree) for child in
            reversed(list(ast.iter_child_nodes(tree)))]
        while stack:
            node, scope = stack.pop()
            self.nodes.append(node)
            scope_nodes[id(scope)].append(node)
            child_scope = scope
            if isinstance(node, ast.Call):
                self.calls.append((node, dotted(node.func)))
                self.enclosing[id(node)] = scope
            elif isinstance(node, _FUNC_TYPES):
                self.functions.append(node)
                scope_nodes.setdefault(id(node), [])
                scope_of[id(node)] = node
                child_scope = node
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
            for child in reversed(list(ast.iter_child_nodes(node))):
                stack.append((child, child_scope))
        self.scopes = [(scope_of[k], v) for k, v in scope_nodes.items()]

#: meta-rule: malformed / rationale-less / unknown-rule suppressions.
BAD_SUPPRESSION = "bad-suppression"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(?P<kind>disable|guarded-by)\s*=\s*"
    r"(?P<value>[^#]*?)\s*$")
_RATIONALE_SPLIT = re.compile(r"\s+--\s+")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass
class Suppression:
    """A ``disable=`` pragma, resolved to the code line it covers."""

    line: int            # the code line the pragma applies to
    pragma_line: int     # where the pragma physically sits
    rules: Tuple[str, ...]
    rationale: str
    used: bool = False


@dataclasses.dataclass
class Guard:
    """A ``guarded-by=`` pragma, resolved to the code line it covers."""

    line: int
    name: str
    rationale: str


class Module:
    """One parsed source file plus its pragmas."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions: List[Suppression] = []
        self.guards: Dict[int, Guard] = {}
        self.comment_lines: Dict[int, str] = {}
        self.bad_pragmas: List[Tuple[int, str]] = []
        self._index: Optional[ModuleIndex] = None
        self._scan_pragmas()

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    @property
    def index(self) -> ModuleIndex:
        if self._index is None:
            self._index = ModuleIndex(self.tree)
        return self._index

    def _scan_pragmas(self) -> None:
        comments: List[Tuple[int, int, str]] = []  # (line, col, text)
        code_lines = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.start[1],
                                     tok.string))
                elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                                      tokenize.INDENT, tokenize.DEDENT,
                                      tokenize.ENDMARKER):
                    code_lines.add(tok.start[0])
        except tokenize.TokenError:  # torn file: pragmas best-effort only
            pass
        sorted_code = sorted(code_lines)

        def effective_line(comment_line: int) -> int:
            if comment_line in code_lines:
                return comment_line        # trailing comment
            for ln in sorted_code:         # standalone: next code line
                if ln > comment_line:
                    return ln
            return comment_line

        for line, _col, text in comments:
            self.comment_lines[line] = text
            m = _PRAGMA_RE.search(text)
            if not m:
                if "graftlint:" in text:
                    self.bad_pragmas.append(
                        (line, f"unparseable graftlint pragma: {text!r}"))
                continue
            kind, value = m.group("kind"), m.group("value")
            parts = _RATIONALE_SPLIT.split(value, maxsplit=1)
            payload = parts[0].strip()
            rationale = parts[1].strip() if len(parts) > 1 else ""
            target = effective_line(line)
            if kind == "guarded-by":
                if not payload:
                    self.bad_pragmas.append(
                        (line, "guarded-by pragma names no sync object"))
                    continue
                self.guards[target] = Guard(target, payload, rationale)
                continue
            rules = tuple(r.strip() for r in payload.split(",")
                          if r.strip())
            if not rules:
                self.bad_pragmas.append(
                    (line, "disable pragma names no rule"))
                continue
            if not rationale:
                self.bad_pragmas.append(
                    (line, f"disable={','.join(rules)} has no rationale "
                           f"(write '-- <why this is safe>')"))
                continue
            self.suppressions.append(
                Suppression(target, line, rules, rationale))

    def has_comment(self, line: int) -> bool:
        """A human comment on ``line`` or the line above (rationale for
        the bare-except rule)."""
        return line in self.comment_lines or \
            (line - 1) in self.comment_lines


class Project:
    """The set of modules one lint run sees (rules may cross-reference,
    e.g. axis declarations vs collective uses, config defs vs reads)."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self._whole_program = None

    def by_basename(self, name: str) -> List[Module]:
        return [m for m in self.modules if m.basename == name]

    def whole_program(self):
        """The repo-wide symbol table / call graph (wholeprogram.py),
        built once per lint invocation and shared by every
        interprocedural rule (17/18/19)."""
        if self._whole_program is None:
            from .wholeprogram import WholeProgram

            self._whole_program = WholeProgram(self)
        return self._whole_program


# -- file discovery ----------------------------------------------------

#: Default lint scope, relative to the repo root: the package, the entry
#: points, the bench harness, and the scripts — NOT tests/ (fixtures
#: trigger rules deliberately).
DEFAULT_SCOPE = ("distributedpytorch_tpu", "main.py", "bench.py",
                 "__graft_entry__.py", "scripts")

_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache"}


def discover(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in _SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif p.endswith(".py") and os.path.exists(p):
            out.append(p)
    return out


def load_project(paths: Iterable[str], root: Optional[str] = None
                 ) -> Tuple[Project, List[Finding]]:
    """Parse every file; unparseable files become findings, not crashes."""
    root = root or os.getcwd()
    modules: List[Module] = []
    findings: List[Finding] = []
    for path in discover(paths):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(Module(path, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding("parse-error", rel,
                                    getattr(e, "lineno", 0) or 0,
                                    f"cannot parse: {e}"))
    return Project(modules), findings


# -- the lint driver ---------------------------------------------------

def lint_project(project: Project, rules=None) -> List[Finding]:
    from . import rules as rules_mod

    active = rules if rules is not None else rules_mod.RULES
    raw: List[Finding] = []
    for rule in active:
        raw.extend(rule.check(project))

    by_rel = {m.rel: m for m in project.modules}
    kept: List[Finding] = []
    for f in sorted(set(raw), key=lambda f: (f.path, f.line, f.rule)):
        mod = by_rel.get(f.path)
        suppressed = False
        if mod is not None and f.rule != BAD_SUPPRESSION:
            for s in mod.suppressions:
                if s.line == f.line and f.rule in s.rules:
                    s.used = True
                    suppressed = True
        if not suppressed:
            kept.append(f)

    rule_names = {r.name for r in active} | {BAD_SUPPRESSION,
                                             "parse-error"}
    for mod in project.modules:
        for line, msg in mod.bad_pragmas:
            kept.append(Finding(BAD_SUPPRESSION, mod.rel, line, msg))
        for s in mod.suppressions:
            unknown = [r for r in s.rules if r not in rule_names]
            if unknown:
                kept.append(Finding(
                    BAD_SUPPRESSION, mod.rel, s.pragma_line,
                    f"disable names unknown rule(s): "
                    f"{', '.join(unknown)}"))
    return sorted(set(kept), key=lambda f: (f.path, f.line, f.rule))


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               rules=None) -> Tuple[List[Finding], int]:
    """Lint ``paths``; returns (findings, files_scanned)."""
    project, parse_findings = load_project(paths, root)
    findings = parse_findings + lint_project(project, rules)
    return (sorted(set(findings), key=lambda f: (f.path, f.line, f.rule)),
            len(project.modules))


# -- changed-only filtering --------------------------------------------

def changed_files(root: str, base: Optional[str] = None) -> Set[str]:
    """Repo-relative paths touched vs the git base: working-tree +
    staged changes (plus untracked .py files), and — with ``base`` — the
    committed diff ``base...HEAD`` too.  Raises RuntimeError when git
    cannot answer (not a repo, bad base): --changed-only is a developer
    convenience and must fail loudly rather than silently lint
    nothing."""
    cmds = [["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"]]
    if base:
        cmds.append(["git", "diff", "--name-only", f"{base}...HEAD"])
    changed: Set[str] = set()
    for cmd in cmds:
        proc = subprocess.run(cmd, cwd=root, capture_output=True,
                              text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed-only: {' '.join(cmd)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        changed.update(ln.strip() for ln in proc.stdout.splitlines()
                       if ln.strip())
    return {c for c in changed if c.endswith(".py")}


# -- rendering ---------------------------------------------------------

def render_findings(findings: Sequence[Finding], files: int,
                    as_json: bool = False,
                    rules: Optional[Sequence[str]] = None,
                    changed_only: bool = False) -> str:
    if as_json:
        payload: Dict[str, object] = {
            "version": 1, "files": files,
            "findings": [f.to_json() for f in findings]}
        if rules is not None:
            # the active rule catalog — gate.sh asserts the
            # whole-program rules (17-19) are in force, not just clean
            payload["rules"] = sorted(rules)
        if changed_only:
            payload["changed_only"] = True
        return json.dumps(payload, indent=2, sort_keys=True)
    suffix = " (changed files only)" if changed_only else ""
    if not findings:
        return f"graftlint: {files} file(s) clean{suffix}"
    lines = [f.render() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s) in "
                 f"{len({f.path for f in findings})} file(s) "
                 f"({files} scanned){suffix}")
    return "\n".join(lines)


def active_rule_names() -> List[str]:
    from . import rules as rules_mod

    return [r.name for r in rules_mod.RULES] + [BAD_SUPPRESSION,
                                                "parse-error"]


def run_cli(argv: Optional[Sequence[str]] = None,
            json_output: bool = False,
            paths: Optional[Sequence[str]] = None,
            root: Optional[str] = None,
            changed_only: bool = False,
            base: Optional[str] = None) -> int:
    """Shared CLI body for ``main.py lint`` and ``scripts/graftlint.py``.

    ``changed_only`` lints only files touched vs the git base (see
    ``changed_files``) — but ALWAYS loads the whole default scope first,
    so the interprocedural rules (17-19) still see every symbol table /
    call-graph edge; only the FINDINGS are filtered to changed files.
    Whole-repo (the default) remains the gate contract; changed-only is
    the fast inner-loop form.
    """
    root = root or os.getcwd()
    scope = [os.path.join(root, p) for p in DEFAULT_SCOPE] \
        if not paths else list(paths)
    findings, files = lint_paths(scope, root=root)
    if changed_only:
        try:
            changed = changed_files(root, base)
        except RuntimeError as e:
            print(f"graftlint: {e}")
            return 2
        findings = [f for f in findings
                    if f.path.replace(os.sep, "/") in changed]
    print(render_findings(findings, files, as_json=json_output,
                          rules=active_rule_names(),
                          changed_only=changed_only))
    return 1 if findings else 0
