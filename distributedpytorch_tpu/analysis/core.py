"""graftlint core: findings, the ``# graftlint:`` pragma grammar, project
loading, and report rendering.

Pragma grammar (parsed with ``tokenize`` so strings never false-match):

  # graftlint: disable=<rule>[,<rule>...] -- <rationale>
      Suppress the named rule(s) on this line (trailing comment) or on
      the next code line (standalone comment line).  The rationale text
      after ``--`` is REQUIRED: a suppression that does not say why is
      itself reported (rule ``bad-suppression``).

  # graftlint: guarded-by=<sync-object> [-- rationale]
      Declares that the attribute assigned on this line is protected by
      the named synchronization object/protocol (a lock attribute, or a
      happens-before edge like ``_queue.join``).  Consumed by the
      ``thread-shared-state`` rule.

Exit contract (CLI): 0 = no findings, 1 = findings, 2 = usage error.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: meta-rule: malformed / rationale-less / unknown-rule suppressions.
BAD_SUPPRESSION = "bad-suppression"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(?P<kind>disable|guarded-by)\s*=\s*"
    r"(?P<value>[^#]*?)\s*$")
_RATIONALE_SPLIT = re.compile(r"\s+--\s+")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass
class Suppression:
    """A ``disable=`` pragma, resolved to the code line it covers."""

    line: int            # the code line the pragma applies to
    pragma_line: int     # where the pragma physically sits
    rules: Tuple[str, ...]
    rationale: str
    used: bool = False


@dataclasses.dataclass
class Guard:
    """A ``guarded-by=`` pragma, resolved to the code line it covers."""

    line: int
    name: str
    rationale: str


class Module:
    """One parsed source file plus its pragmas."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions: List[Suppression] = []
        self.guards: Dict[int, Guard] = {}
        self.comment_lines: Dict[int, str] = {}
        self.bad_pragmas: List[Tuple[int, str]] = []
        self._scan_pragmas()

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def _scan_pragmas(self) -> None:
        comments: List[Tuple[int, int, str]] = []  # (line, col, text)
        code_lines = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.start[1],
                                     tok.string))
                elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                                      tokenize.INDENT, tokenize.DEDENT,
                                      tokenize.ENDMARKER):
                    code_lines.add(tok.start[0])
        except tokenize.TokenError:  # torn file: pragmas best-effort only
            pass
        sorted_code = sorted(code_lines)

        def effective_line(comment_line: int) -> int:
            if comment_line in code_lines:
                return comment_line        # trailing comment
            for ln in sorted_code:         # standalone: next code line
                if ln > comment_line:
                    return ln
            return comment_line

        for line, _col, text in comments:
            self.comment_lines[line] = text
            m = _PRAGMA_RE.search(text)
            if not m:
                if "graftlint:" in text:
                    self.bad_pragmas.append(
                        (line, f"unparseable graftlint pragma: {text!r}"))
                continue
            kind, value = m.group("kind"), m.group("value")
            parts = _RATIONALE_SPLIT.split(value, maxsplit=1)
            payload = parts[0].strip()
            rationale = parts[1].strip() if len(parts) > 1 else ""
            target = effective_line(line)
            if kind == "guarded-by":
                if not payload:
                    self.bad_pragmas.append(
                        (line, "guarded-by pragma names no sync object"))
                    continue
                self.guards[target] = Guard(target, payload, rationale)
                continue
            rules = tuple(r.strip() for r in payload.split(",")
                          if r.strip())
            if not rules:
                self.bad_pragmas.append(
                    (line, "disable pragma names no rule"))
                continue
            if not rationale:
                self.bad_pragmas.append(
                    (line, f"disable={','.join(rules)} has no rationale "
                           f"(write '-- <why this is safe>')"))
                continue
            self.suppressions.append(
                Suppression(target, line, rules, rationale))

    def has_comment(self, line: int) -> bool:
        """A human comment on ``line`` or the line above (rationale for
        the bare-except rule)."""
        return line in self.comment_lines or \
            (line - 1) in self.comment_lines


class Project:
    """The set of modules one lint run sees (rules may cross-reference,
    e.g. axis declarations vs collective uses, config defs vs reads)."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)

    def by_basename(self, name: str) -> List[Module]:
        return [m for m in self.modules if m.basename == name]


# -- file discovery ----------------------------------------------------

#: Default lint scope, relative to the repo root: the package, the entry
#: points, the bench harness, and the scripts — NOT tests/ (fixtures
#: trigger rules deliberately).
DEFAULT_SCOPE = ("distributedpytorch_tpu", "main.py", "bench.py",
                 "__graft_entry__.py", "scripts")

_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache"}


def discover(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in _SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif p.endswith(".py") and os.path.exists(p):
            out.append(p)
    return out


def load_project(paths: Iterable[str], root: Optional[str] = None
                 ) -> Tuple[Project, List[Finding]]:
    """Parse every file; unparseable files become findings, not crashes."""
    root = root or os.getcwd()
    modules: List[Module] = []
    findings: List[Finding] = []
    for path in discover(paths):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(Module(path, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding("parse-error", rel,
                                    getattr(e, "lineno", 0) or 0,
                                    f"cannot parse: {e}"))
    return Project(modules), findings


# -- the lint driver ---------------------------------------------------

def lint_project(project: Project, rules=None) -> List[Finding]:
    from . import rules as rules_mod

    active = rules if rules is not None else rules_mod.RULES
    raw: List[Finding] = []
    for rule in active:
        raw.extend(rule.check(project))

    by_rel = {m.rel: m for m in project.modules}
    kept: List[Finding] = []
    for f in sorted(set(raw), key=lambda f: (f.path, f.line, f.rule)):
        mod = by_rel.get(f.path)
        suppressed = False
        if mod is not None and f.rule != BAD_SUPPRESSION:
            for s in mod.suppressions:
                if s.line == f.line and f.rule in s.rules:
                    s.used = True
                    suppressed = True
        if not suppressed:
            kept.append(f)

    rule_names = {r.name for r in active} | {BAD_SUPPRESSION,
                                             "parse-error"}
    for mod in project.modules:
        for line, msg in mod.bad_pragmas:
            kept.append(Finding(BAD_SUPPRESSION, mod.rel, line, msg))
        for s in mod.suppressions:
            unknown = [r for r in s.rules if r not in rule_names]
            if unknown:
                kept.append(Finding(
                    BAD_SUPPRESSION, mod.rel, s.pragma_line,
                    f"disable names unknown rule(s): "
                    f"{', '.join(unknown)}"))
    return sorted(set(kept), key=lambda f: (f.path, f.line, f.rule))


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               rules=None) -> Tuple[List[Finding], int]:
    """Lint ``paths``; returns (findings, files_scanned)."""
    project, parse_findings = load_project(paths, root)
    findings = parse_findings + lint_project(project, rules)
    return (sorted(set(findings), key=lambda f: (f.path, f.line, f.rule)),
            len(project.modules))


# -- rendering ---------------------------------------------------------

def render_findings(findings: Sequence[Finding], files: int,
                    as_json: bool = False) -> str:
    if as_json:
        return json.dumps(
            {"version": 1, "files": files,
             "findings": [f.to_json() for f in findings]},
            indent=2, sort_keys=True)
    if not findings:
        return f"graftlint: {files} file(s) clean"
    lines = [f.render() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s) in "
                 f"{len({f.path for f in findings})} file(s) "
                 f"({files} scanned)")
    return "\n".join(lines)


def run_cli(argv: Optional[Sequence[str]] = None,
            json_output: bool = False,
            paths: Optional[Sequence[str]] = None,
            root: Optional[str] = None) -> int:
    """Shared CLI body for ``main.py lint`` and ``scripts/graftlint.py``."""
    root = root or os.getcwd()
    scope = [os.path.join(root, p) for p in DEFAULT_SCOPE] \
        if not paths else list(paths)
    findings, files = lint_paths(scope, root=root)
    print(render_findings(findings, files, as_json=json_output))
    return 1 if findings else 0
