"""Whole-program symbol table and call graph for graftlint v2.

PR 3's rules are per-file; the bug classes that survived it are
cross-file: a collective reached through two calls under a
rank-dependent branch, a lock acquired while another module's lock is
held, a mesh axis name threaded through a call chain.  This module
builds, ONCE per lint invocation (memoized on ``Project``), the shared
substrate those interprocedural rules (17/18/19) consume:

  * module naming + per-module import alias tables (absolute, aliased,
    relative at any depth, including imports inside function bodies —
    ``utils.GracefulShutdown._handle`` does ``from . import telemetry``
    inside the handler);
  * registered functions (module-level defs and class methods) and
    classes, with parameter lists, resolved return-annotation types,
    attribute types (``self.x = ClassName(...)``), annotated module
    globals (``_plan: Optional[FaultPlan] = None``) and factory return
    types (``telemetry.get() -> Telemetry``);
  * a resolved call graph: every ``ast.Call`` mapped to the internal
    function it targets where resolution is possible — bare names,
    ``module.func``, ``self.method``, ``self.attr.method``,
    ``var.method`` for vars of known type, and chained factory calls
    (``telemetry.get().event(...)``); unresolvable receivers are
    skipped silently (the rules overapproximate on reachability, never
    on identity);
  * a signal-handler registry (``signal.signal(sig, X)`` with ``X``
    resolved) — the entry points through which rule 18 checks
    handler-reachable non-reentrant locks (the PR 12 deadlock class);
  * a lock inventory: module-level and class-attribute
    ``threading.Lock/RLock/Condition`` objects with reentrancy kinds
    (``Condition()`` defaults to an RLock and is reentrant;
    ``Condition(Lock())`` is not).

Nested functions are merged into their nearest registered enclosing
function (their calls are attributed to it) — a deliberate
overapproximation that keeps closures visible to reachability without
modeling first-class function values.  Single-module, single-level
inheritance is resolved for method lookup; anything fancier falls back
to "unresolved", which the rules treat as silence, not as a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Module, Project, call_name, dotted, kwarg, last_seg, \
    root_seg

_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: lock constructors the inventory recognizes, by alias-expanded name.
_LOCK_CTORS = {"threading.Lock", "threading.RLock",
               "threading.Condition"}

#: lock kinds that deadlock when re-acquired by the same thread —
#: i.e. when a signal handler interrupts a holder (rule 18).
NON_REENTRANT_KINDS = {"Lock", "Condition(Lock)"}


def module_name(rel: str) -> str:
    """Repo-relative path -> dotted module name
    (``distributedpytorch_tpu/data/pipeline.py`` ->
    ``distributedpytorch_tpu.data.pipeline``; a package ``__init__.py``
    names the package itself)."""
    name = rel.replace("\\", "/")
    if name.endswith(".py"):
        name = name[:-3]
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


def display(qname: str) -> str:
    """Human form of a qualified name for findings:
    ``distributedpytorch_tpu.faults:FaultPlan.fire`` ->
    ``faults.FaultPlan.fire``."""
    if ":" not in qname:
        return qname
    modname, sym = qname.split(":", 1)
    sym = sym or "<module>"
    return f"{last_seg(modname)}.{sym}"


class FuncInfo:
    """One registered function (module-level def or class method), or a
    module's top-level statement scope (``qname`` ends ``:<module>``)."""

    __slots__ = ("qname", "modname", "module", "node", "cls", "params",
                 "kwparams", "returns", "env", "lineno")

    def __init__(self, qname: str, modname: str, module: Module,
                 node: ast.AST, cls: Optional[str]):
        self.qname = qname
        self.modname = modname
        self.module = module
        self.node = node
        self.cls = cls
        self.returns: Optional[str] = None
        self.env: Dict[str, str] = {}  # local var -> class qname
        self.lineno = getattr(node, "lineno", 0)
        params: List[str] = []
        kwparams: List[str] = []
        if isinstance(node, _FUNC_TYPES):
            a = node.args
            params = [p.arg for p in a.posonlyargs + a.args]
            if cls is not None and params and params[0] in ("self",
                                                           "cls"):
                params = params[1:]
            kwparams = [p.arg for p in a.kwonlyargs]
        self.params = params
        self.kwparams = set(params) | set(kwparams)

    @property
    def body(self) -> List[ast.stmt]:
        return self.node.body

    @property
    def display(self) -> str:
        return display(self.qname)


class ClassInfo:
    """One class: its direct methods, resolved bases, and the types of
    ``self.<attr>`` assignments resolvable without local context."""

    __slots__ = ("qname", "modname", "module", "node", "attr_types",
                 "bases")

    def __init__(self, qname: str, modname: str, module: Module,
                 node: ast.ClassDef):
        self.qname = qname
        self.modname = modname
        self.module = module
        self.node = node
        self.attr_types: Dict[str, str] = {}  # attr -> class qname
        self.bases: List[str] = []            # resolved base qnames


class WholeProgram:
    """The repo-wide symbol table / call graph.  Build once via
    ``project.whole_program()``; every accessor after construction is
    read-only."""

    def __init__(self, project: Project):
        self.project = project
        self.mod_by_name: Dict[str, Module] = {}
        self.modname_of: Dict[int, str] = {}      # id(Module) -> name
        self.aliases: Dict[str, Dict[str, str]] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.module_scopes: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.global_types: Dict[str, str] = {}    # "mod:var" -> class q
        self.locks: Dict[str, str] = {}           # lock id -> kind
        self.lock_sites: Dict[str, Tuple[Module, int]] = {}
        self.resolved: Dict[int, str] = {}        # id(call) -> qname
        self.call_bound: Dict[int, bool] = {}
        self.call_caller: Dict[int, str] = {}
        self.calls_of: Dict[str, List[ast.Call]] = {}
        self.callees: Dict[str, Set[str]] = {}
        self.call_sites: Dict[str, List[Tuple[str, ast.Call, Module]]] \
            = {}
        #: (handler qname, registering Module, signal.signal() lineno)
        self.handlers: List[Tuple[str, Module, int]] = []
        self._func_of_node: Dict[int, str] = {}
        self._trans: Dict[str, Set[str]] = {}
        self._build_names()
        self._build_symbols()
        self._build_types()
        self._build_callgraph()

    # -- naming and aliases --------------------------------------------

    def _build_names(self) -> None:
        for mod in self.project.modules:
            name = module_name(mod.rel)
            self.mod_by_name[name] = mod
            self.modname_of[id(mod)] = name

    def _package_of(self, mod: Module, modname: str) -> str:
        if mod.basename == "__init__.py":
            return modname
        return modname.rsplit(".", 1)[0] if "." in modname else ""

    def _scan_aliases(self, mod: Module, modname: str) -> None:
        table: Dict[str, str] = {}
        package = self._package_of(mod, modname)
        for node in mod.index.nodes:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        table[alias.asname] = alias.name
                    # a bare `import a.b` binds root "a" to itself;
                    # the identity mapping is implicit in expand()
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = package
                    for _ in range(node.level - 1):
                        base = base.rsplit(".", 1)[0] \
                            if "." in base else ""
                    target = (f"{base}.{node.module}" if node.module
                              else base)
                else:
                    target = node.module or ""
                if not target:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    table[alias.asname or alias.name] = \
                        f"{target}.{alias.name}"
        self.aliases[modname] = table

    def expand(self, modname: str, name: str) -> str:
        """Alias-expand the root segment of a dotted name as used in
        ``modname`` (``rt.barrier`` -> ``…runtime.barrier``)."""
        root = root_seg(name)
        target = self.aliases.get(modname, {}).get(root)
        if target is None:
            return name
        return target + name[len(root):]

    def split_symbol(self, full: str
                     ) -> Tuple[Optional[str], str]:
        """Split an expanded dotted name at the longest known-module
        prefix: ``…analysis.core.Finding`` -> (``…analysis.core``,
        ``Finding``).  (None, full) when no prefix is a module."""
        parts = full.split(".")
        for i in range(len(parts), 0, -1):
            prefix = ".".join(parts[:i])
            if prefix in self.mod_by_name:
                return prefix, ".".join(parts[i:])
        return None, full

    # -- symbols -------------------------------------------------------

    def _build_symbols(self) -> None:
        for mod in self.project.modules:
            modname = self.modname_of[id(mod)]
            self._scan_aliases(mod, modname)
            scope_qname = f"{modname}:<module>"
            self.module_scopes[scope_qname] = FuncInfo(
                scope_qname, modname, mod, mod.tree, None)
            method_ids: Set[int] = set()
            for cls in mod.index.classes:
                cq = f"{modname}:{cls.name}"
                self.classes[cq] = ClassInfo(cq, modname, mod, cls)
                for stmt in cls.body:
                    if isinstance(stmt, _FUNC_TYPES):
                        method_ids.add(id(stmt))
                        q = f"{modname}:{cls.name}.{stmt.name}"
                        self.functions[q] = FuncInfo(
                            q, modname, mod, stmt, cls.name)
                        self._func_of_node[id(stmt)] = q
            # module-level defs: functions whose nearest enclosing
            # function scope is the module itself and that are not
            # class methods (class bodies are not function scopes)
            for scope, nodes in mod.index.scopes:
                if scope is not mod.tree:
                    continue
                for node in nodes:
                    if isinstance(node, _FUNC_TYPES) \
                            and id(node) not in method_ids:
                        q = f"{modname}:{node.name}"
                        self.functions[q] = FuncInfo(
                            q, modname, mod, node, None)
                        self._func_of_node[id(node)] = q

    # -- types ---------------------------------------------------------

    def _resolve_annotation(self, modname: str,
                            ann: Optional[ast.expr]) -> Optional[str]:
        """A type annotation resolved to an internal class qname:
        ``Telemetry``, ``"Tracer"``, ``Optional[FaultPlan]``."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):
            if last_seg(dotted(ann.value)) in ("Optional", "Final",
                                               "ClassVar"):
                return self._resolve_annotation(modname, ann.slice)
            return None
        name = dotted(ann)
        if not name:
            return None
        r = self.resolve_symbol(modname, name)
        if r is not None and r[0] == "class":
            return r[1]
        return None

    def _lock_kind(self, modname: str,
                   value: ast.expr) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        full = self.expand(modname, call_name(value))
        if full not in _LOCK_CTORS:
            return None
        kind = last_seg(full)
        if kind != "Condition":
            return kind
        inner = value.args[0] if value.args else kwarg(value, "lock")
        if inner is None:
            return "Condition"   # stdlib default: RLock -> reentrant
        if isinstance(inner, ast.Call) and self.expand(
                modname, call_name(inner)) == "threading.Lock":
            return "Condition(Lock)"
        return "Condition"

    def non_reentrant(self, lock_id: str) -> bool:
        return self.locks.get(lock_id) in NON_REENTRANT_KINDS

    def _build_types(self) -> None:
        # 1. return annotations (independent of everything else)
        for fi in self.functions.values():
            if isinstance(fi.node, _FUNC_TYPES):
                fi.returns = self._resolve_annotation(
                    fi.modname, fi.node.returns)
        # 2. module globals + module-level locks
        for mod in self.project.modules:
            modname = self.modname_of[id(mod)]
            for stmt in mod.tree.body:
                target = value = ann = None
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    target, value = stmt.targets[0].id, stmt.value
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    target, value = stmt.target.id, stmt.value
                    ann = stmt.annotation
                if target is None:
                    continue
                kind = self._lock_kind(modname, value) \
                    if value is not None else None
                if kind is not None:
                    lid = f"{modname}:{target}"
                    self.locks[lid] = kind
                    self.lock_sites[lid] = (mod, stmt.lineno)
                    continue
                t = self._resolve_annotation(modname, ann) \
                    or (self._ctor_type(modname, value)
                        if value is not None else None)
                if t is not None:
                    self.global_types[f"{modname}:{target}"] = t
        # 3. class attribute types + class-attr locks
        for ci in self.classes.values():
            self._scan_class_attrs(ci)
            for base in ci.node.bases:
                r = self.resolve_symbol(ci.modname, dotted(base))
                if r is not None and r[0] == "class":
                    ci.bases.append(r[1])

    def _ctor_type(self, modname: str,
                   value: ast.expr) -> Optional[str]:
        """Type of a no-context value expression: ``ClassName(...)`` or
        ``factory(...)`` with an annotated return."""
        if not isinstance(value, ast.Call):
            return None
        r = self.resolve_symbol(modname, call_name(value))
        if r is None:
            return None
        kind, q = r
        if kind == "class":
            return q
        fi = self.functions.get(q)
        return fi.returns if fi is not None else None

    def _scan_class_attrs(self, ci: ClassInfo) -> None:
        for stmt in ci.node.body:           # class-body attrs
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self._class_attr(ci, stmt.targets[0].id, stmt.value,
                                 None, stmt.lineno)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                self._class_attr(ci, stmt.target.id, stmt.value,
                                 stmt.annotation, stmt.lineno)
        for node in ast.walk(ci.node):      # self.<attr> = ... anywhere
            targets: Sequence[ast.expr] = ()
            value = ann = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value, ann = [node.target], node.value, \
                    node.annotation
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    self._class_attr(ci, t.attr, value, ann,
                                     node.lineno)

    def _class_attr(self, ci: ClassInfo, attr: str,
                    value: Optional[ast.expr],
                    ann: Optional[ast.expr], lineno: int) -> None:
        kind = self._lock_kind(ci.modname, value) \
            if value is not None else None
        if kind is not None:
            lid = f"{ci.qname}.{attr}"
            self.locks.setdefault(lid, kind)
            self.lock_sites.setdefault(lid, (ci.module, lineno))
            return
        t = self._resolve_annotation(ci.modname, ann) \
            or (self._ctor_type(ci.modname, value)
                if value is not None else None)
        if t is not None:
            ci.attr_types.setdefault(attr, t)

    # -- resolution ----------------------------------------------------

    def resolve_symbol(self, modname: str, name: str
                       ) -> Optional[Tuple[str, str]]:
        """Resolve a dotted name used in ``modname`` to ("func", qname)
        or ("class", qname), None when external/unresolvable."""
        if not name:
            return None
        full = self.expand(modname, name)
        pkg, sym = self.split_symbol(full)
        if pkg is None or not sym:
            # not a module path: try module-local symbols
            pkg, sym = modname, name
        segs = sym.split(".")
        if len(segs) == 1:
            q = f"{pkg}:{sym}"
            if q in self.functions:
                return ("func", q)
            if q in self.classes:
                return ("class", q)
        elif len(segs) == 2:
            q = f"{pkg}:{segs[0]}.{segs[1]}"
            if q in self.functions:
                return ("func", q)
        return None

    def find_method(self, class_qname: str, name: str,
                    _depth: int = 0) -> Optional[str]:
        ci = self.classes.get(class_qname)
        if ci is None or _depth > 3:
            return None
        q = f"{class_qname}.{name}"
        if q in self.functions:
            return q
        for base in ci.bases:
            m = self.find_method(base, name, _depth + 1)
            if m is not None:
                return m
        return None

    def expr_type(self, modname: str, cls: Optional[str],
                  env: Dict[str, str],
                  expr: ast.expr) -> Optional[str]:
        """Class qname of an expression's value, where statically
        knowable; None otherwise."""
        if isinstance(expr, ast.Call):
            tgt = self.resolve_call_target(modname, cls, env, expr)
            if tgt is not None:
                q, _bound = tgt
                if q.endswith(".__init__"):
                    return q[: -len(".__init__")]
                fi = self.functions.get(q)
                return fi.returns if fi is not None else None
            r = self.resolve_symbol(modname, call_name(expr))
            if r is not None and r[0] == "class":
                return r[1]     # class without an own __init__
            return None
        if isinstance(expr, ast.Name):
            t = env.get(expr.id)
            if t is not None:
                return t
            return self.global_types.get(f"{modname}:{expr.id}")
        if isinstance(expr, ast.Attribute):
            d = dotted(expr)
            if d.startswith("self.") and cls is not None \
                    and "." not in d[5:]:
                ci = self.classes.get(f"{modname}:{cls}")
                return ci.attr_types.get(d[5:]) if ci else None
            full = self.expand(modname, d)
            pkg, sym = self.split_symbol(full)
            if pkg is not None and sym and "." not in sym:
                return self.global_types.get(f"{pkg}:{sym}")
        return None

    def resolve_call_target(self, modname: str, cls: Optional[str],
                            env: Dict[str, str], call: ast.Call
                            ) -> Optional[Tuple[str, bool]]:
        """The internal function a call targets, as (qname, bound) —
        ``bound`` True when the receiver fills the ``self`` slot."""
        f = call.func
        if isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Call):
            # chained factory: telemetry.get().event(...)
            rt = self.expr_type(modname, cls, env, f.value)
            if rt is not None:
                m = self.find_method(rt, f.attr)
                if m is not None:
                    return (m, True)
            return None
        name = dotted(f)
        if not name:
            return None
        root = root_seg(name)
        rest = name[len(root) + 1:] if "." in name else ""
        if root == "self" and cls is not None and rest:
            return self._resolve_on_class(f"{modname}:{cls}", rest)
        if rest:
            recv_t = env.get(root) \
                or self.global_types.get(f"{modname}:{root}")
            if recv_t is not None:
                return self._resolve_on_class(recv_t, rest)
        r = self.resolve_symbol(modname, name)
        if r is None:
            return None
        kind, q = r
        if kind == "func":
            # `Cls.meth(obj, …)` resolves unbound: args include self
            fi = self.functions.get(q)
            return (q, False if fi is not None and fi.cls is not None
                    and "." in name else not (fi and fi.cls))
        init = self.find_method(q, "__init__")
        return (init, True) if init is not None else None

    def _resolve_on_class(self, class_qname: str, rest: str
                          ) -> Optional[Tuple[str, bool]]:
        segs = rest.split(".")
        if len(segs) == 1:
            m = self.find_method(class_qname, segs[0])
            return (m, True) if m is not None else None
        if len(segs) == 2:
            ci = self.classes.get(class_qname)
            attr_t = ci.attr_types.get(segs[0]) if ci else None
            if attr_t is not None:
                m = self.find_method(attr_t, segs[1])
                return (m, True) if m is not None else None
        return None

    def resolve_func_ref(self, modname: str, cls: Optional[str],
                         env: Dict[str, str],
                         expr: ast.expr) -> Optional[str]:
        """A bare function REFERENCE (no call): ``self._handle``,
        ``module.func`` — used for signal-handler targets."""
        d = dotted(expr)
        if not d:
            return None
        if d.startswith("self.") and cls is not None \
                and "." not in d[5:]:
            return self.find_method(f"{modname}:{cls}", d[5:])
        r = self.resolve_symbol(modname, d)
        if r is not None and r[0] == "func":
            return r[1]
        return None

    def resolve_lock(self, modname: str, cls: Optional[str],
                     env: Dict[str, str],
                     expr: ast.expr) -> Optional[str]:
        """A lock-acquisition receiver resolved to an inventory id:
        ``self._lock``, ``_lineage_lock``, ``mod._lock``, or a typed
        local's attribute."""
        d = dotted(expr)
        if not d:
            return None
        if d.startswith("self.") and cls is not None:
            lid = f"{modname}:{cls}.{d[5:]}"
            if lid in self.locks:
                return lid
            # the attribute may be inherited
            ci = self.classes.get(f"{modname}:{cls}")
            for base in (ci.bases if ci else ()):
                lid = f"{base}.{d[5:]}"
                if lid in self.locks:
                    return lid
            return None
        if "." not in d:
            lid = f"{modname}:{d}"
            return lid if lid in self.locks else None
        root, rest = d.split(".", 1)
        recv_t = env.get(root) \
            or self.global_types.get(f"{modname}:{root}")
        if recv_t is not None:
            lid = f"{recv_t}.{rest}"
            return lid if lid in self.locks else None
        full = self.expand(modname, d)
        pkg, sym = self.split_symbol(full)
        if pkg is not None and sym and "." not in sym:
            lid = f"{pkg}:{sym}"
            return lid if lid in self.locks else None
        return None

    # -- call graph ----------------------------------------------------

    def _build_callgraph(self) -> None:
        for mod in self.project.modules:
            modname = self.modname_of[id(mod)]
            scope = self.module_scopes[f"{modname}:<module>"]
            self._walk(mod, modname, mod.tree, None, scope)

    def _seed_env(self, fi: FuncInfo) -> None:
        if not isinstance(fi.node, _FUNC_TYPES):
            return
        a = fi.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            t = self._resolve_annotation(fi.modname, p.annotation)
            if t is not None:
                fi.env.setdefault(p.arg, t)

    def _walk(self, mod: Module, modname: str, node: ast.AST,
              cls: Optional[str], fi: FuncInfo) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_TYPES):
                q = self._func_of_node.get(id(child))
                nfi = self.functions.get(q) if q is not None else None
                if nfi is not None:
                    self._seed_env(nfi)
                    self._walk(mod, modname, child, nfi.cls, nfi)
                else:
                    # nested def: merge into the enclosing function
                    self._walk(mod, modname, child, cls, fi)
                continue
            if isinstance(child, ast.ClassDef):
                self._walk(mod, modname, child, child.name, fi)
                continue
            if isinstance(child, ast.Call):
                self._record_call(mod, modname, cls, fi, child)
            elif isinstance(child, ast.Assign) \
                    and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                # walk the value first so chained calls resolve, then
                # bind the local's type
                self._walk(mod, modname, child, cls, fi)
                t = self.expr_type(modname, cls, fi.env, child.value)
                if t is not None:
                    fi.env[child.targets[0].id] = t
                continue
            self._walk(mod, modname, child, cls, fi)

    def _record_call(self, mod: Module, modname: str,
                     cls: Optional[str], fi: FuncInfo,
                     call: ast.Call) -> None:
        caller = fi.qname
        self.call_caller[id(call)] = caller
        self.calls_of.setdefault(caller, []).append(call)
        cn = call_name(call)
        if (cn == "signal.signal" or cn.endswith(".signal.signal")) \
                and len(call.args) >= 2:
            h = self.resolve_func_ref(modname, cls, fi.env,
                                      call.args[1])
            if h is not None:
                self.handlers.append((h, mod, call.lineno))
        tgt = self.resolve_call_target(modname, cls, fi.env, call)
        if tgt is not None:
            q, bound = tgt
            self.resolved[id(call)] = q
            self.call_bound[id(call)] = bound
            self.callees.setdefault(caller, set()).add(q)
            self.call_sites.setdefault(q, []).append(
                (caller, call, mod))
        # recurse into receiver + arguments (nested calls)
        self._walk(mod, modname, call, cls, fi)

    # -- reachability --------------------------------------------------

    def transitive_callees(self, qname: str) -> Set[str]:
        cached = self._trans.get(qname)
        if cached is not None:
            return cached
        seen: Set[str] = set()
        stack = [qname]
        while stack:
            q = stack.pop()
            for c in self.callees.get(q, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        self._trans[qname] = seen
        return seen

    def call_path(self, start: str, targets: Set[str]
                  ) -> Optional[List[str]]:
        """Shortest call-graph path from ``start`` to any of
        ``targets`` (inclusive of both ends), for finding messages."""
        if start in targets:
            return [start]
        prev: Dict[str, str] = {}
        frontier = [start]
        seen = {start}
        while frontier:
            nxt: List[str] = []
            for q in frontier:
                for c in sorted(self.callees.get(q, ())):
                    if c in seen:
                        continue
                    seen.add(c)
                    prev[c] = q
                    if c in targets:
                        path = [c]
                        while path[-1] != start:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    nxt.append(c)
            frontier = nxt
        return None

    def all_scopes(self) -> List[FuncInfo]:
        """Every analyzable body: registered functions plus each
        module's top-level scope."""
        return list(self.functions.values()) \
            + list(self.module_scopes.values())
