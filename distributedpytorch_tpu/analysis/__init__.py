"""graftlint: JAX/TPU-aware static analysis for this framework.

The paper's value proposition is a *correct* SPMD hot path, and the
hazard classes that break it — host syncs inside per-step loops (the
reference's own ``.item()`` bug, ref classif.py:61-62), impure
computation inside traced functions, mismatched collective axis names,
reused PRNG keys, missing buffer donation, unlocked thread-shared
state — are invisible to pytest but mechanically detectable.  This
package is the detector:

  * :mod:`core` — findings, the ``# graftlint:`` pragma grammar,
    project loading, human/JSON reports;
  * :mod:`rules` — the rule catalog (see ``rules.RULES``);
  * :mod:`transfer_guard` — the runtime sanitizer leg: a 1-epoch CPU
    smoke under ``jax.transfer_guard`` that catches silent device->host
    transfers the static pass cannot see.

Entry points: ``python main.py lint`` and ``scripts/graftlint.py``
(static pass, exit 0 = clean), ``scripts/graftlint.py --smoke``
(sanitizer).  Both gate in ``scripts/gate.sh``.
"""

from .core import Finding, Project, lint_paths, render_findings  # noqa: F401
from .rules import RULES  # noqa: F401
