"""graftlint: JAX/TPU-aware static analysis for this framework.

The paper's value proposition is a *correct* SPMD hot path, and the
hazard classes that break it — host syncs inside per-step loops (the
reference's own ``.item()`` bug, ref classif.py:61-62), impure
computation inside traced functions, mismatched collective axis names,
reused PRNG keys, missing buffer donation, unlocked thread-shared
state — are invisible to pytest but mechanically detectable.  This
package is the detector:

  * :mod:`core` — findings, the ``# graftlint:`` pragma grammar,
    project loading, the per-module :class:`~core.ModuleIndex` (one
    cached AST traversal shared by every rule), human/JSON reports;
  * :mod:`wholeprogram` — the whole-program core: repo-wide symbol
    table (import aliases, classes, factory return types, annotated
    globals), resolved call graph with transitive closure, lock
    inventory, and signal-handler registry.  Built once per project,
    memoized on :meth:`~core.Project.whole_program`; only the
    interprocedural rules (17-19) trigger the build;
  * :mod:`rules` — the rule catalog (see ``rules.RULES``): 16 per-file
    rules plus three interprocedural ones — collective-divergence
    (SPMD collectives under rank-dependent control flow),
    lock-order-cycle (acquisition cycles + signal handlers reaching
    non-reentrant locks), mesh-axis-propagation (axis names flowing
    through call chains into collectives);
  * :mod:`transfer_guard` — the runtime sanitizer leg: a 1-epoch CPU
    smoke under ``jax.transfer_guard`` that catches silent device->host
    transfers the static pass cannot see.

Entry points: ``python main.py lint`` and ``scripts/graftlint.py``
(static pass, exit 0 = clean), ``--changed-only`` to report findings
only in git-changed files (the whole program is still loaded so the
interprocedural rules stay sound — whole-repo remains the gate
default), ``scripts/graftlint.py --smoke`` (sanitizer).  All gate in
``scripts/gate.sh``.
"""

from .core import Finding, Project, lint_paths, render_findings  # noqa: F401
from .rules import RULES  # noqa: F401
from .wholeprogram import WholeProgram  # noqa: F401
