"""The graftlint rule catalog — framework-specific AST rules.

Each rule is an object with ``name``, ``description`` and
``check(project) -> Iterator[Finding]``.  Rules are deliberately
repo-aware (they know the step-driving modules, the mesh constructors,
the thread-spawning classes) — this is a framework linter, not a
general-purpose one.  Every rule has positive and negative fixtures in
tests/test_graftlint.py.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, Module, Project, call_name, dotted, kwarg, \
    last_seg, root_seg
from .wholeprogram import FuncInfo, WholeProgram, display


# -- shared AST helpers (dotted/call_name/... live in core.py now, so
# -- wholeprogram.py shares them without importing the rule catalog) ---

def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class Rule:
    name = ""
    description = ""

    def check(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: Module, line: int, message: str) -> Finding:
        return Finding(self.name, mod.rel, line, message)


# -- 1. host-sync-in-step-loop ----------------------------------------

class HostSyncInStepLoop(Rule):
    """The paper's own bug class (ref classif.py:61-62 per-batch
    ``.item()``): a blocking device->host sync inside a per-step loop
    serializes the host against every dispatch.  Per-epoch syncs are
    fine; per-batch ones are findings.  Applies to the step-driving
    modules (train/engine.py, cli.py and fixtures named like them)."""

    name = "host-sync-in-step-loop"
    description = ("jax.device_get/.item()/float()/np.asarray() inside "
                   "a per-step loop (per-epoch is allowed)")
    TARGET_BASENAMES = {"engine.py", "cli.py"}

    def _is_step_iter(self, node: ast.expr) -> bool:
        """``for ... in loader.epoch(e)`` / ``enumerate(loader.epoch(e))``
        / ``range(...batches_per_epoch...)`` style iterators."""
        for call in walk_calls(node):
            cn = call_name(call)
            if last_seg(cn) in ("epoch", "_threaded_epoch",
                                "_host_batches"):
                return True
            if last_seg(cn) == "range" and any(
                    "batches_per_epoch" in dotted(a) or
                    "nb_iters" in dotted(a)
                    for a in ast.walk(call) if isinstance(
                        a, (ast.Name, ast.Attribute))):
                return True
        return False

    def _sync_calls(self, body: List[ast.stmt]
                    ) -> Iterator[Tuple[int, str]]:
        for stmt in body:
            for call in walk_calls(stmt):
                cn = call_name(call)
                if last_seg(cn) == "device_get":
                    yield call.lineno, f"{cn}() blocks on device values"
                elif last_seg(cn) == "item" and not call.args:
                    yield (call.lineno,
                           ".item() forces a device sync every step")
                elif cn in ("float", "int") and call.args:
                    yield (call.lineno,
                           f"{cn}() on a device value blocks; keep "
                           f"per-step metrics on device")
                elif cn in ("np.asarray", "np.array", "numpy.asarray",
                            "numpy.array"):
                    yield (call.lineno,
                           f"{cn}() copies device->host every step")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.basename not in self.TARGET_BASENAMES:
                continue
            for node in mod.index.nodes:
                if isinstance(node, ast.For) \
                        and self._is_step_iter(node.iter):
                    for line, msg in self._sync_calls(node.body):
                        yield self.finding(
                            mod, line,
                            f"host sync in per-step loop: {msg} "
                            f"(accumulate on device, sync per epoch)")


# -- 2. trace-impurity -------------------------------------------------

_IMPURE_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                 "numpy.array", "np.copy"}
_IMPURE_ROOTS = {"time", "logging", "telemetry", "tel"}


class TraceImpurity(Rule):
    """Side effects inside jit/pjit/shard_map-traced functions run at
    TRACE time (once, on abstract values), not per step — prints and
    clocks silently measure nothing, numpy materializes tracers, and
    attribute/nonlocal mutation leaks trace-time state."""

    name = "trace-impurity"
    description = ("print/time/logging/telemetry/np-materialization or "
                   "nonlocal mutation inside a traced function")

    _WRAPPERS = {"jit", "pjit", "shard_map"}

    def _partial_target(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Call) \
                and last_seg(call_name(node)) == "partial" and node.args:
            return dotted(node.args[0])
        return None

    def _wrapped_name(self, node: ast.expr,
                      local_partials: Dict[str, str]) -> Optional[str]:
        """The function name a jit/shard_map call wraps, if resolvable:
        a Name, ``self.x``, ``functools.partial(f, ...)``, or a local
        variable previously bound to a partial."""
        target = self._partial_target(node)
        if target:
            return last_seg(target)
        name = dotted(node)
        if name:
            short = last_seg(name)
            return local_partials.get(short, short)
        if isinstance(node, ast.Call) \
                and last_seg(call_name(node)) in self._WRAPPERS \
                and node.args:
            return self._wrapped_name(node.args[0], local_partials)
        return None

    def _collect_traced_roots(self, mod: Module) -> Set[str]:
        roots: Set[str] = set()
        # local `x = functools.partial(f, ...)` bindings, module-wide
        local_partials: Dict[str, str] = {}
        for node in mod.index.nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._partial_target(node.value)
                if t:
                    local_partials[node.targets[0].id] = last_seg(t)
        for node in mod.index.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dn = dotted(dec)
                    if last_seg(dn) in self._WRAPPERS:
                        roots.add(node.name)
                    elif isinstance(dec, ast.Call):
                        cn = call_name(dec)
                        if last_seg(cn) in self._WRAPPERS:
                            roots.add(node.name)
                        elif last_seg(cn) == "partial" and dec.args \
                                and last_seg(dotted(dec.args[0])) \
                                in self._WRAPPERS:
                            roots.add(node.name)
            elif isinstance(node, ast.Call) \
                    and last_seg(call_name(node)) in self._WRAPPERS \
                    and node.args:
                wrapped = self._wrapped_name(node.args[0],
                                             local_partials)
                if wrapped:
                    roots.add(wrapped)
        return roots

    def _function_table(self, mod: Module
                        ) -> Dict[str, ast.FunctionDef]:
        table: Dict[str, ast.FunctionDef] = {}
        for node in mod.index.functions:
            table.setdefault(node.name, node)
        return table

    def _expand(self, roots: Set[str],
                table: Dict[str, ast.FunctionDef]) -> Set[str]:
        """Transitive closure: any function of this module *referenced*
        from a traced body (called directly, via self.x, or passed to
        scan/vmap/partial) is traced too."""
        traced = set(r for r in roots if r in table)
        frontier = list(traced)
        while frontier:
            fn = table[frontier.pop()]
            for node in ast.walk(fn):
                ref = None
                if isinstance(node, ast.Attribute):
                    ref = node.attr
                elif isinstance(node, ast.Name):
                    ref = node.id
                if ref and ref in table and ref not in traced:
                    traced.add(ref)
                    frontier.append(ref)
        return traced

    def _impure(self, fn: ast.FunctionDef) -> Iterator[Tuple[int, str]]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn == "print":
                    yield node.lineno, "print() runs at trace time only"
                elif root_seg(cn) in _IMPURE_ROOTS and "." in cn:
                    yield (node.lineno,
                           f"{cn}() is a host side effect; it runs at "
                           f"trace time, not per step")
                elif cn in _IMPURE_CALLS:
                    yield (node.lineno,
                           f"{cn}() materializes tracers on host")
                elif last_seg(cn) == "device_get":
                    yield node.lineno, f"{cn}() on a tracer"
            elif isinstance(node, (ast.Nonlocal, ast.Global)):
                kind = ("nonlocal" if isinstance(node, ast.Nonlocal)
                        else "global")
                yield (node.lineno,
                       f"{kind} mutation from a traced function leaks "
                       f"trace-time state")
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        yield (node.lineno,
                               f"self.{t.attr} assignment inside a "
                               f"traced function runs once at trace "
                               f"time")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            table = self._function_table(mod)
            traced = self._expand(self._collect_traced_roots(mod), table)
            for name in sorted(traced):
                for line, msg in self._impure(table[name]):
                    yield self.finding(
                        mod, line, f"in traced function {name!r}: {msg}")


# -- 3. collective-axis-consistency -----------------------------------

_COLLECTIVES = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
                "all_gather": 1, "ppermute": 1, "psum_scatter": 1,
                "all_to_all": 1, "axis_index": 0}


def declared_axes(project: Project) -> Set[str]:
    """Every axis name some mesh constructor declares: ``*_AXIS``
    string constants, plus literal ``Mesh(..., (names...))`` tuples.
    Shared by rules 3 and 19."""
    axes: Set[str] = set()
    for mod in project.modules:
        for node in mod.index.nodes:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_AXIS") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                axes.add(node.value.value)
            elif isinstance(node, ast.Call) \
                    and last_seg(call_name(node)) == "Mesh":
                cands = list(node.args[1:2]) + [
                    v for v in (kwarg(node, "axis_names"),)
                    if v is not None]
                for cand in cands:
                    if isinstance(cand, (ast.Tuple, ast.List)):
                        for el in cand.elts:
                            if isinstance(el, ast.Constant) \
                                    and isinstance(el.value, str):
                                axes.add(el.value)
    return axes


def axis_constants(project: Project) -> Dict[str, str]:
    """``*_AXIS`` constant name -> axis string, repo-wide.  Shared by
    rules 3 and 19."""
    consts: Dict[str, str] = {}
    for mod in project.modules:
        for node in mod.index.nodes:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_AXIS") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                consts[node.targets[0].id] = node.value.value
    return consts


class CollectiveAxisConsistency(Rule):
    """Every ``lax.psum/pmean/all_gather/ppermute/axis_index`` axis name
    must be an axis some mesh constructor declares (runtime.make_mesh's
    data/model/seq, or any literal ``Mesh(..., (names...))``) — a typo'd
    axis surfaces as an unbound-axis error only for the configs that
    reach that code path."""

    name = "collective-axis-consistency"
    description = "collective axis names must match declared mesh axes"

    def _param_defaults(self, mod: Module) -> Dict[Tuple[str, str], str]:
        """(function, param) -> string default, for axis args passed by
        parameter (``def f(..., axis_name='model')``)."""
        out: Dict[Tuple[str, str], str] = {}
        for node in mod.index.functions:
            a = node.args
            pos = a.posonlyargs + a.args
            for param, default in zip(pos[len(pos) - len(a.defaults):],
                                      a.defaults):
                if isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    out[(node.name, param.arg)] = default.value
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    out[(node.name, param.arg)] = default.value
        return out

    def _resolve(self, node: ast.expr, consts: Dict[str, str],
                 enclosing: Optional[str],
                 defaults: Dict[Tuple[str, str], str]) -> Optional[str]:
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            return node.value
        name = dotted(node)
        if last_seg(name) in consts:
            return consts[last_seg(name)]
        if enclosing and isinstance(node, ast.Name):
            return defaults.get((enclosing, node.id))
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        declared = declared_axes(project)
        consts = axis_constants(project)
        for mod in project.modules:
            defaults = self._param_defaults(mod)
            enclosing = mod.index.enclosing  # id(call) -> scope node
            for call, cn in mod.index.calls:
                seg = last_seg(cn)
                if seg not in _COLLECTIVES or "lax" not in cn:
                    continue
                pos = _COLLECTIVES[seg]
                axis_arg = kwarg(call, "axis_name")
                if axis_arg is None and len(call.args) > pos:
                    axis_arg = call.args[pos]
                if axis_arg is None:
                    continue
                scope = enclosing.get(id(call))
                axis = self._resolve(axis_arg, consts,
                                     getattr(scope, "name", None),
                                     defaults)
                if axis is not None and axis not in declared:
                    yield self.finding(
                        mod, call.lineno,
                        f"{cn}(axis {axis!r}) names an axis no mesh "
                        f"constructor declares "
                        f"(declared: {sorted(declared)})")


# -- 4. prng-reuse -----------------------------------------------------

_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "fold_key",
               "root_key", "clone"}
_KEY_DERIVERS = {"split", "fold_in", "fold_key", "PRNGKey", "key",
                 "root_key", "clone", "key_data", "wrap_key_data"}


class PrngReuse(Rule):
    """A PRNGKey consumed twice without an intervening split/fold_in
    draws IDENTICAL randomness at both sites — augmentation noise,
    dropout masks, init values silently correlate."""

    name = "prng-reuse"
    description = ("PRNGKey variable consumed by two samplers without "
                   "an intervening split/fold_in")

    def _key_vars(self, fn: ast.FunctionDef) -> Set[str]:
        keys: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if isinstance(node.value, ast.Call) \
                    and last_seg(call_name(node.value)) in _KEY_MAKERS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        keys.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        keys.update(e.id for e in t.elts
                                    if isinstance(e, ast.Name))
        return keys

    def _consumptions(self, stmt: ast.stmt, keys: Set[str]
                      ) -> List[Tuple[str, int]]:
        """Key consumptions in one statement: a key passed to a
        jax.random sampler, or inside an ``rngs=`` mapping, or in a
        dict handed to ``.init``/``.apply``."""
        out: List[Tuple[str, int]] = []
        for call in walk_calls(stmt):
            cn = call_name(call)
            seg = last_seg(cn)
            if "random" in cn and seg not in _KEY_DERIVERS:
                for arg in call.args:
                    if isinstance(arg, ast.Name) and arg.id in keys:
                        out.append((arg.id, arg.lineno))
            rngs = kwarg(call, "rngs")
            if rngs is not None:
                for used in names_in(rngs) & keys:
                    out.append((used, rngs.lineno))
            if seg in ("init", "apply"):
                for arg in call.args:
                    if isinstance(arg, ast.Dict):
                        for v in arg.values:
                            if isinstance(v, ast.Name) and v.id in keys:
                                out.append((v.id, v.lineno))
        return out

    def _assigned(self, stmt: ast.stmt) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    names.update(names_in(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and node.target is not None:
                names.update(names_in(node.target))
        return names

    def _scan(self, body: List[ast.stmt], keys: Set[str],
              counts: Dict[str, int], out: List[Tuple[str, int]],
              in_loop: bool = False) -> None:
        for stmt in body:
            if isinstance(stmt, ast.If):
                base = dict(counts)
                branches = []
                for branch in (stmt.body, stmt.orelse):
                    c = dict(base)
                    self._scan(branch, keys, c, out, in_loop)
                    branches.append(c)
                for k in keys:
                    counts[k] = max(b.get(k, 0) for b in branches)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                # loop body runs "twice": consumption of an outer key on
                # each iteration is reuse, unless re-derived inside
                for _ in range(2):
                    self._scan(stmt.body, keys, counts, out,
                               in_loop=True)
                self._scan(stmt.orelse, keys, counts, out, in_loop)
                continue
            if isinstance(stmt, ast.Try):
                self._scan(stmt.body, keys, counts, out, in_loop)
                for h in stmt.handlers:
                    self._scan(h.body, keys, counts, out, in_loop)
                self._scan(stmt.orelse, keys, counts, out, in_loop)
                self._scan(stmt.finalbody, keys, counts, out, in_loop)
                continue
            if isinstance(stmt, ast.With):
                self._scan(stmt.body, keys, counts, out, in_loop)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs analyzed separately
            for var, line in self._consumptions(stmt, keys):
                counts[var] = counts.get(var, 0) + 1
                if counts[var] == 2:
                    out.append((var, line))
            for var in self._assigned(stmt) & keys:
                counts[var] = 0  # rebound: a fresh key
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not any(s in mod.source
                       for s in ("key", "split", "clone")):
                continue  # no key construction: nothing to reuse
            for node in mod.index.functions:
                keys = self._key_vars(node)
                if not keys:
                    continue
                reused: List[Tuple[str, int]] = []
                self._scan(node.body, keys, {}, reused)
                for var, line in reused:
                    yield self.finding(
                        mod, line,
                        f"PRNG key {var!r} consumed twice without an "
                        f"intervening split/fold_in — both sites draw "
                        f"identical randomness")


# -- 5. missing-donation ----------------------------------------------

class MissingDonation(Rule):
    """A jitted train step that takes a TrainState without donating it
    holds TWO copies of params+optimizer state live across the update —
    the single biggest avoidable HBM cost in a training loop."""

    name = "missing-donation"
    description = ("jitted train-step-like function (TrainState first "
                   "arg) without donate_argnums")

    def _defs(self, mod: Module) -> Dict[str, ast.FunctionDef]:
        return {n.name: n for n in mod.index.functions}

    def _train_state_first_arg(self, fn: ast.FunctionDef) -> bool:
        args = [a for a in fn.args.posonlyargs + fn.args.args
                if a.arg != "self"]
        if not args:
            return False
        first = args[0]
        ann = dotted(first.annotation) if first.annotation else ""
        return first.arg == "state" or last_seg(ann) == "TrainState"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            defs = self._defs(mod)
            for call, cn in mod.index.calls:
                if last_seg(cn) not in ("jit", "pjit"):
                    continue
                if kwarg(call, "donate_argnums") is not None \
                        or kwarg(call, "donate_argnames") is not None:
                    continue
                if not call.args:
                    continue
                wrapped = last_seg(dotted(call.args[0]))
                fn = defs.get(wrapped)
                if fn is None or "train" not in fn.name:
                    continue
                if self._train_state_first_arg(fn):
                    yield self.finding(
                        mod, call.lineno,
                        f"jit({fn.name}) takes a TrainState but does "
                        f"not donate it: two copies of params+opt "
                        f"state stay live across the update (add "
                        f"donate_argnums=0)")
            # decorator form: @jax.jit / @partial(jax.jit, ...) on a def
            for fn in defs.values():
                if "train" not in fn.name \
                        or not self._train_state_first_arg(fn):
                    continue
                for dec in fn.decorator_list:
                    if last_seg(dotted(dec)) in ("jit", "pjit"):
                        yield self.finding(
                            mod, fn.lineno,
                            f"@jit on {fn.name} without donate_argnums "
                            f"(TrainState is copied, not reused)")
                    elif isinstance(dec, ast.Call) \
                            and last_seg(call_name(dec)) in ("jit",
                                                             "pjit") \
                            and kwarg(dec, "donate_argnums") is None \
                            and kwarg(dec, "donate_argnames") is None:
                        yield self.finding(
                            mod, fn.lineno,
                            f"@jit on {fn.name} without donate_argnums "
                            f"(TrainState is copied, not reused)")


# -- 6. thread-shared-state -------------------------------------------

_THREADSAFE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                     "Event", "Lock", "RLock", "Condition", "Semaphore",
                     "BoundedSemaphore", "Barrier", "local", "deque"}


class ThreadSharedState(Rule):
    """In a class that spawns ``threading.Thread``, an attribute written
    by the thread target and read elsewhere without the class's lock (or
    a ``# graftlint: guarded-by=<sync>`` annotation at its __init__
    assignment) is a data race candidate."""

    name = "thread-shared-state"
    description = ("attribute written in a thread target, read "
                   "elsewhere without lock or guarded-by annotation")

    def _methods(self, cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _thread_targets(self, cls: ast.ClassDef
                        ) -> List[ast.FunctionDef]:
        """Functions handed to threading.Thread(target=...): methods
        (``self.x``) or nested defs of the spawning method."""
        out: List[ast.FunctionDef] = []
        methods = self._methods(cls)
        for meth in methods.values():
            nested = {n.name: n for n in ast.walk(meth)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not meth}
            for call in walk_calls(meth):
                if last_seg(call_name(call)) != "Thread":
                    continue
                target = kwarg(call, "target")
                if target is None:
                    continue
                tn = last_seg(dotted(target))
                if tn in methods:
                    out.append(methods[tn])
                elif tn in nested:
                    out.append(nested[tn])
        return out

    def _self_attr_writes(self, fn: ast.FunctionDef) -> Dict[str, int]:
        writes: Dict[str, int] = {}
        for node in ast.walk(fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    writes.setdefault(t.attr, node.lineno)
        return writes

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_seg(call_name(node.value)) in (
                        "Lock", "RLock", "Condition"):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        locks.add(t.attr)
        return locks

    def _exempt_attrs(self, cls: ast.ClassDef, mod: Module) -> Set[str]:
        """Attrs of inherently thread-safe type, or annotated
        guarded-by at any of their assignments."""
        exempt: Set[str] = set()
        for node in ast.walk(cls):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                value = node.value
                if isinstance(value, ast.Call) and last_seg(
                        call_name(value)) in _THREADSAFE_TYPES:
                    exempt.add(t.attr)
                if node.lineno in mod.guards:
                    exempt.add(t.attr)
        return exempt

    def _unguarded_accesses(self, fn: ast.FunctionDef, attr: str,
                            locks: Set[str]) -> List[int]:
        """Accesses to self.<attr> in ``fn`` outside every
        ``with self.<lock>:`` block."""
        guarded_ranges: List[Tuple[int, int]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    d = dotted(item.context_expr)
                    if d.startswith("self.") \
                            and d.split(".")[1] in locks:
                        guarded_ranges.append(
                            (node.lineno, node.end_lineno or node.lineno))
        lines: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr == attr \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                if not any(a <= node.lineno <= b
                           for a, b in guarded_ranges):
                    lines.append(node.lineno)
        return lines

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if "Thread" not in mod.source:
                continue  # no thread construction: no shared state
            for cls in mod.index.nodes:
                if not isinstance(cls, ast.ClassDef):
                    continue
                targets = self._thread_targets(cls)
                if not targets:
                    continue
                locks = self._lock_attrs(cls)
                exempt = self._exempt_attrs(cls, mod)
                target_names = {t.name for t in targets}
                for target in targets:
                    for attr, wline in sorted(
                            self._self_attr_writes(target).items()):
                        if attr in exempt:
                            continue
                        for meth in self._methods(cls).values():
                            if meth.name in target_names:
                                continue
                            for line in self._unguarded_accesses(
                                    meth, attr, locks):
                                yield self.finding(
                                    mod, line,
                                    f"self.{attr} is written by thread "
                                    f"target {target.name!r} (line "
                                    f"{wline}) but accessed in "
                                    f"{meth.name!r} without holding a "
                                    f"class lock; lock it or annotate "
                                    f"the __init__ assignment with "
                                    f"'# graftlint: guarded-by=<sync>'")


# -- 7. config-drift ---------------------------------------------------

class ConfigDrift(Rule):
    """config.py constants, Config dataclass fields, and argparse dests
    that are defined but never read anywhere — dead configuration
    surface that silently diverges from behavior."""

    name = "config-drift"
    description = ("config constant / Config field / CLI dest defined "
                   "but never read")

    def _config_defs(self, mod: Module):
        constants: Dict[str, int] = {}
        fields: Dict[str, int] = {}
        dests: Dict[str, int] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.isupper():
                constants[node.targets[0].id] = node.lineno
        for node in mod.index.nodes:
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        fields[stmt.target.id] = stmt.lineno
            elif isinstance(node, ast.Call) \
                    and last_seg(call_name(node)) == "add_argument":
                dest = kwarg(node, "dest")
                if isinstance(dest, ast.Constant) \
                        and isinstance(dest.value, str):
                    dests[dest.value] = node.lineno
                elif dest is None:
                    longs = [a.value for a in node.args
                             if isinstance(a, ast.Constant)
                             and isinstance(a.value, str)
                             and a.value.startswith("--")]
                    if longs:
                        dests[longs[0][2:].replace("-", "_")] = \
                            node.lineno
        return constants, fields, dests

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.by_basename("config.py"):
            constants, fields, dests = self._config_defs(mod)
            used_names: Set[str] = set()
            used_attrs: Set[str] = set()
            getattr_strings: Set[str] = set()
            for other in project.modules:
                for node in other.index.nodes:
                    if isinstance(node, ast.Name) \
                            and isinstance(node.ctx, ast.Load):
                        used_names.add(node.id)
                    elif isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Load):
                        used_attrs.add(node.attr)
                    elif isinstance(node, ast.Call) \
                            and dotted(node.func) == "getattr" \
                            and len(node.args) >= 2 \
                            and isinstance(node.args[1], ast.Constant) \
                            and isinstance(node.args[1].value, str):
                        getattr_strings.add(node.args[1].value)
            for name, line in sorted(constants.items()):
                if name not in used_names:
                    yield self.finding(
                        mod, line,
                        f"constant {name} is defined but never read "
                        f"(delete it or wire it)")
            for name, line in sorted(fields.items()):
                # construction keywords don't count: a field that is
                # parsed+stored but never READ is exactly the drift
                if name not in used_attrs \
                        and name not in getattr_strings:
                    yield self.finding(
                        mod, line,
                        f"Config field {name!r} is never read — dead "
                        f"configuration surface (delete or plumb it)")
            for name, line in sorted(dests.items()):
                if name not in used_attrs \
                        and name not in getattr_strings:
                    yield self.finding(
                        mod, line,
                        f"CLI flag dest {name!r} is parsed but never "
                        f"consumed (delete the flag or plumb it)")


# -- 8. bare-except ----------------------------------------------------

class BareExcept(Rule):
    """``except Exception:`` / bare ``except:`` without a rationale
    comment swallows real defects (and keyboard interrupts, for the
    bare form).  Narrow the type, or say WHY broad is right, on the
    except line or the line above."""

    name = "bare-except"
    description = ("except Exception / bare except without a rationale "
                   "comment")

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, (ast.Name, ast.Attribute)):
            return last_seg(dotted(t)) in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(last_seg(dotted(e)) in ("Exception",
                                               "BaseException")
                       for e in t.elts)
        return False

    def _has_rationale(self, mod: Module,
                       handler: ast.ExceptHandler) -> bool:
        """A comment on the except line, the line above, or leading the
        handler body (before/at its first statement)."""
        if mod.has_comment(handler.lineno):
            return True
        first_body = handler.body[0].lineno if handler.body \
            else handler.lineno
        return any(ln in mod.comment_lines
                   for ln in range(handler.lineno + 1, first_body + 1))

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for node in mod.index.nodes:
                if isinstance(node, ast.ExceptHandler) \
                        and self._is_broad(node) \
                        and not self._has_rationale(mod, node):
                    what = (ast.unparse(node.type)
                            if node.type is not None else "bare except")
                    yield self.finding(
                        mod, node.lineno,
                        f"broad handler ({what}) without a rationale "
                        f"comment — narrow the exception type or say "
                        f"why broad is correct")


class RetryWithoutBackoff(Rule):
    """A loop that catches an exception and goes around again with no
    delay is a hot-spin retry: against a struggling filesystem or a
    coordinator that is still coming up it hammers the failing resource
    thousands of times per second instead of giving it room to recover.
    Every retry loop must either sleep between attempts (ideally
    exponential backoff with jitter — ``faults.RetryPolicy``) or bound
    each attempt with a ``timeout=`` so the wait IS the pacing (the
    bounded-queue put/get pattern in data/pipeline.py)."""

    name = "retry-without-backoff"
    description = ("loop retries a caught exception with no sleep/"
                   "backoff and no timeout-bounded attempt")

    # A call whose name looks like pacing: time.sleep, asyncio.sleep, a
    # policy's .call/.retry wrapper, or anything *backoff*-named.
    PACING_SEGS = ("sleep", "backoff")
    PACING_WRAPPERS = ("retry", "call")

    def _paces(self, node: ast.AST) -> bool:
        for call in walk_calls(node):
            cn = last_seg(call_name(call)).lower()
            if any(seg in cn for seg in self.PACING_SEGS):
                return True
            if cn in self.PACING_WRAPPERS \
                    and "retry" in call_name(call).lower():
                return True
        return False

    def _bounded(self, try_node: ast.Try) -> bool:
        """An attempt whose blocking call carries ``timeout=`` paces
        itself — the wait between retries is the timeout."""
        return any(kwarg(call, "timeout") is not None
                   for stmt in try_node.body
                   for call in walk_calls(stmt))

    # Iterator-exhaustion signals are loop control flow, not failures
    # being retried (the pipeline's queue-drain loops catch these).
    CONTROL_EXCS = ("StopIteration", "StopAsyncIteration",
                    "GeneratorExit")

    def _is_retry_loop(self, loop) -> bool:
        """Retry loops re-attempt the SAME operation: ``while`` loops,
        and ``for`` loops counting attempts over ``range()``.  A ``for``
        over a collection is per-item processing — skipping a bad item
        and moving on is not a retry."""
        if isinstance(loop, ast.While):
            return True
        it = loop.iter
        if not (isinstance(it, ast.Call)
                and last_seg(call_name(it)) == "range"):
            return False
        hints = {dotted(loop.target).lower()} | {
            n.lower() for a in it.args for n in names_in(a)}
        return any(h in name for name in hints
                   for h in ("attempt", "retr", "tries"))

    def _control_flow_only(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        elts = t.elts if isinstance(t, ast.Tuple) else [t] if t else []
        return bool(elts) and all(
            last_seg(dotted(e)) in self.CONTROL_EXCS for e in elts)

    def _swallows(self, handler: ast.ExceptHandler) -> bool:
        """True when the handler just eats the error and lets the loop
        spin: only pass/continue/bare-expression (logging) statements.
        raise/return/break escape; an assignment captures the error for
        structured handling elsewhere."""
        return all(isinstance(stmt, (ast.Pass, ast.Continue, ast.Expr))
                   for stmt in handler.body)

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for loop in mod.index.nodes:
                if not isinstance(loop, (ast.For, ast.While)) \
                        or not self._is_retry_loop(loop):
                    continue
                if self._paces(loop):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Try):
                        continue
                    if self._bounded(node):
                        continue
                    for handler in node.handlers:
                        if self._swallows(handler) \
                                and not self._control_flow_only(handler):
                            yield self.finding(
                                mod, handler.lineno,
                                "retry loop with no backoff: the "
                                "handler swallows the error and spins "
                                "— sleep between attempts (see "
                                "faults.RetryPolicy) or bound the "
                                "attempt with timeout=")


class ProfilerTraceLeak(Rule):
    """``jax.profiler.start_trace`` begins a GLOBAL capture; a path that
    raises (or simply returns) before the matching ``stop_trace`` leaves
    the profiler running for the rest of the process — every later step
    is traced into an ever-growing buffer, and a later ``start_trace``
    (the next anomaly capture, a --profile run) dies on "already
    started".  The stop must be reachable on every path: either a
    ``stop_trace`` inside a ``finally`` in the same function, or — for
    the split start/stop state-machine shape (flightrec.AnomalyDetector
    starts in one method, stops K steps later in another) — a method of
    the same class whose ``finally`` stops it, so the object's close()
    path is the guarantee.  The ``with jax.profiler.trace(...):``
    context manager is always safe (it never parses as start_trace)."""

    name = "profiler-trace-leak"
    description = ("jax.profiler.start_trace without a stop_trace in a "
                   "finally (same function or a method of the same "
                   "class)")

    def _stops_in_finally(self, scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for call in walk_calls(stmt):
                        if last_seg(call_name(call)) == "stop_trace":
                            return True
        return False

    def _starts(self, node: ast.AST, fn, cls, out: List[Tuple]) -> None:
        """Every start_trace call with its enclosing function/class."""
        for child in ast.iter_child_nodes(node):
            nfn, ncls = fn, cls
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nfn = child
            elif isinstance(child, ast.ClassDef):
                ncls, nfn = child, None
            elif isinstance(child, ast.Call) \
                    and last_seg(call_name(child)) == "start_trace":
                out.append((child, fn, cls))
            self._starts(child, nfn, ncls, out)

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if "start_trace" not in mod.source:
                continue
            starts: List[Tuple] = []
            self._starts(mod.tree, None, None, starts)
            for call, fn, cls in starts:
                scope = fn if fn is not None else mod.tree
                if self._stops_in_finally(scope):
                    continue
                if cls is not None and any(
                        self._stops_in_finally(meth)
                        for meth in cls.body
                        if isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))
                        and meth is not fn):
                    continue
                where = (f"function {fn.name!r}" if fn is not None
                         else "module scope")
                yield self.finding(
                    mod, call.lineno,
                    f"start_trace in {where} has no stop_trace in a "
                    f"finally on the same function (or a method of the "
                    f"same class): an exception leaks a running "
                    f"profiler — wrap the traced region in "
                    f"try/finally: jax.profiler.stop_trace()")


# -- 11. mixed-precision-accum -----------------------------------------

_HALF_DTYPE_SEGS = {"bfloat16", "float16"}
_HALF_DTYPE_STRINGS = {"bfloat16", "float16", "bf16", "f16"}


class MixedPrecisionAccum(Rule):
    """Accumulating in a half-precision dtype silently rots accuracy:
    bf16 has ~8 mantissa bits, so a running sum loses every addend below
    ~1/256 of the accumulator — loss curves drift, metrics saturate, and
    nothing crashes.  The PrecisionPolicy contract (precision.py) keeps
    params/compute in bf16 but ALL accumulation in f32; this rule flags
    code that breaks it: a reduction asked to accumulate in a half dtype
    (``jnp.sum(x, dtype=jnp.bfloat16)``), or a half-dtype accumulator
    buffer (``acc = jnp.zeros(n, jnp.bfloat16)``) that is then summed
    into in place or carried through ``lax.scan``.  Casting the RESULT
    of an f32 reduction down is fine and is not flagged."""

    name = "mixed-precision-accum"
    description = ("reduction or running accumulator in a half dtype "
                   "(bf16/f16) — accumulate in f32, cast the result")

    _CREATORS = {"zeros", "ones", "full", "zeros_like", "ones_like",
                 "full_like"}
    _REDUCERS = {"sum", "mean", "average", "cumsum", "prod", "cumprod"}
    _ACC_OPS = (ast.Add, ast.Sub, ast.Mult)

    def _is_half_dtype(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value in _HALF_DTYPE_STRINGS
        return last_seg(dotted(node)) in _HALF_DTYPE_SEGS

    def _creator_half_dtype(self, call: ast.Call) -> bool:
        seg = last_seg(call_name(call))
        if seg not in self._CREATORS:
            return False
        dt = kwarg(call, "dtype")
        if dt is None:
            # positional dtype: zeros/ones/*_like(x, dtype) at arg 1,
            # full/full_like(shape, fill, dtype) at arg 2
            pos = 2 if seg in ("full", "full_like") else 1
            if len(call.args) > pos:
                dt = call.args[pos]
        return dt is not None and self._is_half_dtype(dt)

    def _half_acc_vars(self, nodes: List[ast.AST]) -> Dict[str, int]:
        """name -> creation line of half-dtype buffers assigned in the
        scope (a node list from mod.index.scopes)."""
        out: Dict[str, int] = {}
        for node in nodes:
            if not isinstance(node, ast.Assign):
                continue
            pairs: List[Tuple[ast.expr, ast.expr]] = []
            for t in node.targets:
                if isinstance(t, ast.Name):
                    pairs.append((t, node.value))
                elif isinstance(t, (ast.Tuple, ast.List)) \
                        and isinstance(node.value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(node.value.elts):
                    pairs.extend(zip(t.elts, node.value.elts))
            for target, value in pairs:
                if isinstance(target, ast.Name) \
                        and isinstance(value, ast.Call) \
                        and self._creator_half_dtype(value):
                    out.setdefault(target.id, value.lineno)
        return out

    def _accumulations(self, nodes: List[ast.AST],
                       halfvars: Dict[str, int]
                       ) -> Iterator[Tuple[int, str, str]]:
        """(line, var, how) for each accumulation into a half buffer."""
        for node in nodes:
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id in halfvars \
                    and isinstance(node.op, self._ACC_OPS):
                yield node.lineno, node.target.id, "augmented in place"
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in halfvars \
                            and t.id in names_in(node.value):
                        yield (node.lineno, t.id,
                               "rebound to an expression of itself")
            elif isinstance(node, ast.Call) \
                    and last_seg(call_name(node)) == "scan" \
                    and len(node.args) >= 2:
                carried = names_in(node.args[1]) & set(halfvars)
                for var in sorted(carried):
                    yield (node.lineno, var,
                           "carried through lax.scan (summed every "
                           "step)")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            # direct half-dtype reductions, anywhere in the module
            for call, cn in mod.index.calls:
                if last_seg(cn) in self._REDUCERS:
                    dt = kwarg(call, "dtype")
                    if dt is not None and self._is_half_dtype(dt):
                        yield self.finding(
                            mod, call.lineno,
                            f"{cn}(dtype=half) accumulates "
                            f"in a half dtype — reduce in f32 (the "
                            f"default) and cast the result instead")
            # half-dtype accumulator buffers, per enclosing scope
            seen: Set[Tuple[int, str]] = set()
            for _scope, nodes in mod.index.scopes:
                halfvars = self._half_acc_vars(nodes)
                if not halfvars:
                    continue
                for line, var, how in self._accumulations(nodes,
                                                          halfvars):
                    if (line, var) in seen:
                        continue
                    seen.add((line, var))
                    yield self.finding(
                        mod, line,
                        f"half-dtype buffer {var!r} (created line "
                        f"{halfvars[var]}) is {how}: bf16/f16 "
                        f"accumulation drops addends below ~1/256 of "
                        f"the running value — allocate the accumulator "
                        f"in f32 and cast once at the end")


# -- 12. collective-in-cleanup ----------------------------------------

class CollectiveInCleanup(Rule):
    """A collective in an ``except``/``finally`` block is a deadlock
    trap: cleanup paths are exactly where ranks DIVERGE — one rank got
    here through a failure its peers didn't see, so the peers are not
    in (and may never reach) the matching collective, and the cleanup
    hangs on the very condition it was cleaning up after.  This is the
    failure mode the elastic teardown is built around (elastic.py:
    survivors must never run a barrier the dead rank can't join — the
    jaxlib shutdown barrier is the canonical offender).  Failure paths
    must be collective-free, or first re-establish agreement through a
    bounded mechanism (runtime.agree_health with --health-timeout).
    Deliberate exceptions carry a rationale comment on the call line or
    the line above, same contract as bare-except."""

    name = "collective-in-cleanup"
    description = ("collective call inside except/finally — peers that "
                   "didn't take this path never reach it (deadlock)")

    # Cross-rank rendezvous: jax.lax collectives, multihost_utils
    # helpers, and this repo's own agreement wrappers (runtime.py).
    COLLECTIVES = {
        "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
        "ppermute", "psum_scatter", "process_allgather",
        "sync_global_devices", "broadcast_one_to_all",
        "host_local_array_to_global_array",
        "global_array_to_host_local_array", "barrier", "agree_health",
        "any_process",
    }

    def _has_rationale(self, mod: Module, line: int) -> bool:
        return mod.has_comment(line) or (line - 1) in mod.comment_lines

    def _cleanup_bodies(self, mod: Module
                        ) -> Iterator[Tuple[str, List[ast.stmt]]]:
        for node in mod.index.nodes:
            if isinstance(node, ast.Try):
                for handler in node.handlers:
                    yield "except", handler.body
                if node.finalbody:
                    yield "finally", node.finalbody

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for where, body in self._cleanup_bodies(mod):
                for stmt in body:
                    for call in walk_calls(stmt):
                        if last_seg(call_name(call)) \
                                not in self.COLLECTIVES:
                            continue
                        if self._has_rationale(mod, call.lineno):
                            continue
                        yield self.finding(
                            mod, call.lineno,
                            f"{call_name(call)}() inside a {where} "
                            f"block: a rank that didn't take this "
                            f"path never reaches the matching "
                            f"collective and this cleanup deadlocks "
                            f"— move it before the try, gate it on "
                            f"agreement, or comment why every rank "
                            f"provably gets here")


# -- 13. wall-clock-in-measurement ------------------------------------

class WallClockInMeasurement(Rule):
    """``time.time()`` in a subtraction is a duration measured on the
    wall clock — which NTP can step backwards or slew mid-interval, so
    the "duration" can come out negative or off by the adjustment.  The
    repo's clock contract (telemetry.py docstring) is three-way: ``ts``
    = time.time() stamp for humans, NEVER subtracted; ``mono`` =
    time.monotonic() for cross-record ordering; durations via
    time.perf_counter().  The ledger/timeline/flightrec reconciliation
    all assume it — one wall-clock duration corrupts a whole epoch row.
    Flags ``time.time()`` appearing as a subtraction operand, directly
    or through a variable assigned from it.  Deliberate exceptions
    (e.g. comparing two wall stamps ACROSS hosts, where wall clock is
    the point) carry a rationale comment on the line or the line above,
    same contract as bare-except."""

    name = "wall-clock-in-measurement"
    description = ("time.time() used in a subtraction (duration on the "
                   "wall clock) — stamp with time(), measure with "
                   "perf_counter()")

    def _has_rationale(self, mod: Module, line: int) -> bool:
        return mod.has_comment(line) or (line - 1) in mod.comment_lines

    def _is_wall_call(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) \
            and call_name(node) == "time.time"

    def _tainted(self, nodes: List[ast.AST]) -> Set[str]:
        """Names bound to a raw time.time() result in this scope.
        Scope-strict (mod.index.scopes): a name bound from time.time()
        in one function is a different binding in another, and leaking
        taint across scopes turns the rule into noise."""
        out: Set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) \
                    and self._is_wall_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for _scope, nodes in mod.index.scopes:
                tainted = self._tainted(nodes)
                for node in nodes:
                    if isinstance(node, ast.BinOp) \
                            and isinstance(node.op, ast.Sub):
                        operands = (node.left, node.right)
                    elif isinstance(node, ast.AugAssign) \
                            and isinstance(node.op, ast.Sub):
                        operands = (node.target, node.value)
                    else:
                        continue
                    culprit = None
                    for opnd in operands:
                        if self._is_wall_call(opnd):
                            culprit = "time.time()"
                            break
                        if isinstance(opnd, ast.Name) \
                                and opnd.id in tainted:
                            culprit = (f"{opnd.id!r} (assigned from "
                                       f"time.time())")
                            break
                    if culprit is None:
                        continue
                    if self._has_rationale(mod, node.lineno):
                        continue
                    yield self.finding(
                        mod, node.lineno,
                        f"{culprit} in a subtraction measures a "
                        f"duration on the wall clock, which NTP can "
                        f"step mid-interval — use time.perf_counter() "
                        f"for durations (clock contract: ts=stamp, "
                        f"mono=ordering, perf_counter=duration), or "
                        f"comment why wall time is the point here")


# -- 14. blocking-h2d-in-step-loop ------------------------------------

class BlockingH2dInStepLoop(Rule):
    """A host->device transfer issued inline in the per-step loop is
    consumed by the very next dispatch, so the H2D copy sits on the
    critical path instead of overlapping the previous step's compute —
    the exact gap ``--device-prefetch`` exists to close (the loader's
    dedicated transfer thread issues sharded ``device_put`` N batches
    ahead; data/pipeline.py).  Same spirit as host-sync-in-step-loop
    but for the other direction of the PCIe link.  Applies to the
    step-driving modules; per-epoch transfers (outside the step loop)
    are fine.  Deliberate exceptions carry a rationale comment on the
    line or the line above, same contract as wall-clock-in-measurement.
    """

    name = "blocking-h2d-in-step-loop"
    description = ("jax.device_put / make_array_from_process_local_data "
                   "/ block_until_ready inline in a per-step loop — let "
                   "the loader's --device-prefetch transfer thread own "
                   "H2D")
    TARGET_BASENAMES = {"engine.py", "cli.py"}
    TRANSFERS = {"device_put", "device_put_sharded",
                 "device_put_replicated",
                 "make_array_from_process_local_data"}

    # step-loop iterator shapes are rule 1's, verbatim
    _is_step_iter = HostSyncInStepLoop._is_step_iter

    def _has_rationale(self, mod: Module, line: int) -> bool:
        return mod.has_comment(line) or (line - 1) in mod.comment_lines

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.basename not in self.TARGET_BASENAMES:
                continue
            for node in mod.index.nodes:
                if not (isinstance(node, ast.For)
                        and self._is_step_iter(node.iter)):
                    continue
                for stmt in node.body:
                    for call in walk_calls(stmt):
                        cn = call_name(call)
                        seg = last_seg(cn)
                        if seg in self.TRANSFERS:
                            what = (f"{cn}() transfers host->device on "
                                    f"the step's critical path")
                        elif seg == "block_until_ready":
                            what = (f"{cn}() stalls the step loop until "
                                    f"the transfer/step lands")
                        else:
                            continue
                        if self._has_rationale(mod, call.lineno):
                            continue
                        yield self.finding(
                            mod, call.lineno,
                            f"blocking H2D in per-step loop: {what} — "
                            f"use the loader's --device-prefetch "
                            f"transfer thread (or move the transfer "
                            f"out of the loop), or comment why inline "
                            f"is the point here")


# -- 15. unbounded-queue-in-server ------------------------------------

class UnboundedQueueInServer(Rule):
    """A server that queues without a bound turns overload into
    unbounded memory growth and seconds-later timeouts for EVERYONE,
    instead of an immediate 503 for the overflow — the backpressure
    contract the serving tier is built on (serving/batcher.py sheds at
    ``--serve-queue``; ISSUE 15).  Two shapes in serving/request-handler
    modules are findings:

      * a ``queue.Queue()`` / ``SimpleQueue()`` / ``LifoQueue()``
        constructed without a positive maxsize — the stdlib default is
        infinite;
      * an ``.append()`` / ``.appendleft()`` / ``.put()`` onto a
        collection inside a ``while True:`` producer loop with no
        ``len()``-based guard anywhere in the loop body — the
        accumulate-forever shape.

    Deliberate exceptions carry a rationale comment on the line or the
    line above (same contract as wall-clock-in-measurement): e.g. an
    unbounded deque whose bound is enforced at an explicit admit()
    check so overflow is ANSWERED rather than silently dropped."""

    name = "unbounded-queue-in-server"
    description = ("queue.Queue()/producer-loop append without a "
                   "maxsize or backpressure bound in serving/request-"
                   "handler code — shed load with an answer, never "
                   "queue unboundedly")
    TARGET_BASENAMES = {"server.py", "batcher.py", "handler.py",
                        "handlers.py"}
    QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue"}
    APPENDS = {"append", "appendleft", "put", "put_nowait"}

    _has_rationale = BlockingH2dInStepLoop._has_rationale

    def _targets(self, mod: Module) -> bool:
        return (mod.basename in self.TARGET_BASENAMES
                or "serving" in mod.rel.replace("\\", "/").split("/")[:-1])

    def _unbounded_ctor(self, call: ast.Call) -> bool:
        """queue.Queue() with no positive bound.  SimpleQueue has no
        maxsize parameter at all — it is always unbounded."""
        cn = call_name(call)
        if last_seg(cn) not in self.QUEUE_CTORS:
            return False
        if root_seg(cn) not in ("queue", "multiprocessing", "mp", ""):
            return False
        if last_seg(cn) == "SimpleQueue":
            return True
        bound = call.args[0] if call.args else kwarg(call, "maxsize")
        if bound is None:
            return True
        # maxsize=0 and maxsize=-1 are the stdlib's spellings of
        # "infinite"; any other literal/expression counts as a bound.
        return (isinstance(bound, ast.Constant)
                and isinstance(bound.value, int) and bound.value <= 0)

    def _loop_has_shed_guard(self, loop: ast.While) -> bool:
        """A len()-based comparison anywhere in the loop body: the
        producer checks how much is queued before appending."""
        for n in ast.walk(loop):
            if isinstance(n, ast.Call) and call_name(n) == "len":
                return True
            if isinstance(n, ast.Attribute) and n.attr in ("qsize",
                                                           "full",
                                                           "depth"):
                return True
        return False

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not self._targets(mod):
                continue
            for node in mod.index.nodes:
                if isinstance(node, ast.Call) \
                        and self._unbounded_ctor(node):
                    if self._has_rationale(mod, node.lineno):
                        continue
                    yield self.finding(
                        mod, node.lineno,
                        f"unbounded {call_name(node)}() in server code: "
                        f"the stdlib default maxsize is infinite, so "
                        f"overload becomes memory growth + mass "
                        f"timeouts — pass a maxsize and shed overflow "
                        f"with an answer (503), or comment why this "
                        f"queue is bounded elsewhere")
                    continue
                if not (isinstance(node, ast.While)
                        and isinstance(node.test, ast.Constant)
                        and node.test.value is True):
                    continue
                if self._loop_has_shed_guard(node):
                    continue
                for call in walk_calls(node):
                    if not isinstance(call.func, ast.Attribute) \
                            or call.func.attr not in self.APPENDS:
                        continue
                    if self._has_rationale(mod, call.lineno):
                        continue
                    yield self.finding(
                        mod, call.lineno,
                        f".{call.func.attr}() in a 'while True' "
                        f"producer loop with no len()/qsize()/full() "
                        f"check: requests accumulate without bound "
                        f"under overload — check the depth and shed "
                        f"(answer 503) before enqueueing, or comment "
                        f"why growth is bounded here")


# -- 16. unbounded-metric-cardinality ---------------------------------

class UnboundedMetricCardinality(Rule):
    """A metric name built by interpolating a runtime value — a request
    id, a rank, a path, a hostname — mints a NEW series per distinct
    value.  The registry (telemetry.Telemetry keeps one object per
    name), every scrape body, and every downstream collector grow
    without bound: the classic exporter-OOM, and the fleet collector
    re-exports whatever the ranks mint, so one bad name multiplies by
    the world size (ISSUE 16).  Identity belongs in a LABEL with a
    bounded value set, or in the event's attrs — never in the series
    name.

    A finding is a call to ``counter()`` / ``gauge()`` / ``histogram()``
    (any receiver: ``tel.counter``, ``telemetry.get().histogram``) — or
    a ``Histogram(...)`` construction — whose name argument is built at
    call time: an f-string with at least one interpolated field, a
    ``"..." % x`` format, a ``"...".format(...)`` call, or a string
    concatenation involving a non-literal.  A constant name, however
    composed of literals, is fine.

    Deliberate exceptions carry a rationale comment on the line or the
    line above (same contract as wall-clock-in-measurement): e.g. a
    name interpolated from a FIXED enum the comment enumerates."""

    name = "unbounded-metric-cardinality"
    description = ("metric/series name interpolated from runtime values "
                   "in telemetry/serving/fleet code — per-value series "
                   "grow the registry and every scrape without bound; "
                   "use a bounded label or attrs instead")
    TARGET_BASENAMES = {"telemetry.py", "goodput.py", "fleet.py",
                        "tracing.py", "slo.py"}
    METRIC_CALLS = {"counter", "gauge", "histogram"}

    _has_rationale = BlockingH2dInStepLoop._has_rationale

    def _targets(self, mod: Module) -> bool:
        return (mod.basename in self.TARGET_BASENAMES
                or "serving" in mod.rel.replace("\\", "/").split("/")[:-1])

    def _dynamic(self, node: ast.AST) -> Optional[str]:
        """How the name is built at call time, or None for static."""
        if isinstance(node, ast.JoinedStr):
            if any(isinstance(v, ast.FormattedValue)
                   for v in node.values):
                return "an f-string interpolation"
            return None  # f-string with no fields: static after all
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Mod):
                return "a %-format"
            if isinstance(node.op, ast.Add):
                left = self._dynamic(node.left)
                right = self._dynamic(node.right)
                if left or right:
                    return left or right
                if not (isinstance(node.left, ast.Constant)
                        and isinstance(node.right, ast.Constant)):
                    return "a runtime string concatenation"
            return None
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "format":
            return "a .format() call"
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not self._targets(mod):
                continue
            for node, cn in mod.index.calls:
                if not node.args:
                    continue
                callee = last_seg(cn)
                if callee.lower() not in self.METRIC_CALLS \
                        and callee != "Histogram":
                    continue
                how = self._dynamic(node.args[0])
                if how is None:
                    continue
                if self._has_rationale(mod, node.lineno):
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"metric name passed to {callee}() is built by "
                    f"{how}: every distinct runtime value mints a new "
                    f"series, growing the registry and every scrape "
                    f"body without bound (and the fleet re-export "
                    f"multiplies it by world size) — move the identity "
                    f"into a bounded label/attrs, or comment why the "
                    f"value set is fixed")


# -- 17. collective-divergence (whole-program) -------------------------

#: jax.lax collectives — every rank in the axis must call them.
_LAX_COLLECTIVE_SEGS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                        "all_to_all", "ppermute", "psum_scatter",
                        "pbroadcast"}
#: multihost rendezvous helpers — every PROCESS must call them.
_MULTIHOST_SEGS = {"sync_global_devices", "process_allgather",
                   "broadcast_one_to_all",
                   "host_local_array_to_global_array"}
#: condition fragments that mean "this branch is rank-dependent".
_RANK_CALL_SEGS = {"process_index", "is_main", "is_coordinator"}


def _leaf_collective(cn: str) -> Optional[str]:
    """The collective-registry leaf a raw dotted call name names, or
    None.  lax collectives require a lax-ish prefix so a method named
    ``psum`` on some class doesn't count; the multihost helpers are
    distinctive enough to match by segment."""
    seg = last_seg(cn)
    if seg in _LAX_COLLECTIVE_SEGS and "lax" in cn:
        return seg
    if seg in _MULTIHOST_SEGS:
        return seg
    return None


def _rank_named(seg: str) -> bool:
    return seg == "rank" or seg.endswith("_rank") \
        or seg.startswith("rank_")


def _terminates(body: List[ast.stmt]) -> bool:
    """The branch provably exits the function/loop: ends in
    return/raise/break/continue or a sys.exit/os._exit call."""
    if not body:
        return False
    tail = body[-1]
    if isinstance(tail, (ast.Return, ast.Raise, ast.Break,
                         ast.Continue)):
        return True
    return isinstance(tail, ast.Expr) \
        and isinstance(tail.value, ast.Call) \
        and last_seg(call_name(tail.value)) in ("exit", "_exit")


class CollectiveDivergence(Rule):
    """The SPMD contract: every rank executes the same collectives in
    the same order, or the world hangs at the next mismatched
    rendezvous.  This rule finds the static form of that hang: a
    collective (a jax.lax/multihost call directly, or any function that
    transitively reaches one over the whole-program call graph —
    runtime.barrier, checkpoint saves with orbax barriers, elastic
    rendezvous) that executes only under RANK-DEPENDENT control flow:

      * lexically inside an ``if`` whose condition reads
        ``process_index()`` / ``is_main()`` / a ``*rank*``-named value
        (directly or through a tainted local), or
      * after an early-exit guard on such a condition
        (``if not is_main(): return`` ... collective), which is the
        same divergence one indentation level flatter.

    Uniform conditions (``process_count() > 1``) evaluate identically
    on every rank and are NOT rank-dependent.  Deliberate
    coordinator-only protocols (elastic publishes where the
    non-coordinators are provably parked elsewhere) carry a
    ``# graftlint: disable=collective-divergence -- <why>`` pragma."""

    name = "collective-divergence"
    description = ("collective reachable only under rank-dependent "
                   "control flow — ranks that skip it hang the world")

    def _reaching(self, wp: WholeProgram,
                  direct: Dict[str, Set[str]],
                  cache: Dict[str, Set[str]], qname: str) -> Set[str]:
        got = cache.get(qname)
        if got is None:
            got = set(direct.get(qname, ()))
            for callee in wp.transitive_callees(qname):
                got |= direct.get(callee, set())
            cache[qname] = got
        return got

    def _scope_assigns(self, body: List[ast.stmt],
                       out: List[ast.Assign]) -> None:
        """Assign statements lexically in THIS scope (nested def/class
        bodies are their own scopes and are skipped)."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign):
                out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._scope_assigns(sub, out)
            for h in getattr(stmt, "handlers", ()):
                self._scope_assigns(h.body, out)

    def _tainted_locals(self, fi: FuncInfo) -> Set[str]:
        out: Set[str] = set()
        assigns: List[ast.Assign] = []
        self._scope_assigns(fi.body, assigns)
        for node in assigns:
            tainted = False
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call) and last_seg(
                        call_name(sub)) in _RANK_CALL_SEGS:
                    tainted = True
                elif isinstance(sub, (ast.Name, ast.Attribute)) \
                        and _rank_named(last_seg(dotted(sub))):
                    tainted = True
            if tainted:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _taint_reason(self, test: ast.expr,
                      tainted: Set[str]) -> Optional[str]:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                seg = last_seg(call_name(sub))
                if seg in _RANK_CALL_SEGS:
                    return f"{call_name(sub)}()"
            elif isinstance(sub, (ast.Name, ast.Attribute)):
                seg = last_seg(dotted(sub))
                if _rank_named(seg):
                    return seg
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return f"{sub.id} (rank-derived)"
        return None

    def _flag_calls(self, wp, direct, cache, fi, node, reason,
                    out: List[Tuple[ast.Call, str, str]]) -> None:
        for call in walk_calls(node):
            cn = call_name(call)
            leaf = _leaf_collective(cn)
            if leaf is not None:
                out.append((call, f"{cn}()", reason))
                continue
            q = wp.resolved.get(id(call))
            if q is None:
                continue
            leaves = self._reaching(wp, direct, cache, q)
            if leaves:
                out.append((
                    call,
                    f"{cn}() (reaches "
                    f"{'/'.join(sorted(leaves))} via {display(q)})",
                    reason))

    def _scan(self, wp, direct, cache, fi, body: List[ast.stmt],
              tainted: Set[str], diverged: Optional[str],
              out: List[Tuple[ast.Call, str, str]]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate scopes, analyzed on their own
            if isinstance(stmt, (ast.If, ast.While)):
                reason = self._taint_reason(stmt.test, tainted)
                if reason is not None:
                    why = (f"inside a branch on {reason} "
                           f"(line {stmt.lineno})")
                    self._flag_calls(wp, direct, cache, fi, stmt,
                                     why, out)
                    if isinstance(stmt, ast.If) \
                            and (_terminates(stmt.body)
                                 or _terminates(stmt.orelse)):
                        diverged = (f"after the rank-dependent early "
                                    f"exit on {reason} "
                                    f"(line {stmt.lineno})")
                    continue
                self._scan(wp, direct, cache, fi, stmt.body, tainted,
                           diverged, out)
                self._scan(wp, direct, cache, fi, stmt.orelse, tainted,
                           diverged, out)
                continue
            if diverged is not None:
                self._flag_calls(wp, direct, cache, fi, stmt,
                                 diverged, out)
            sub_bodies: List[List[ast.stmt]] = []
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                sub_bodies = [stmt.body, stmt.orelse]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                sub_bodies = [stmt.body]
            elif isinstance(stmt, ast.Try):
                sub_bodies = ([stmt.body, stmt.orelse, stmt.finalbody]
                              + [h.body for h in stmt.handlers])
            for sub in sub_bodies:
                self._scan(wp, direct, cache, fi, sub, tainted,
                           diverged, out)

    def check(self, project: Project) -> Iterator[Finding]:
        wp = project.whole_program()
        cn_of = {id(c): cn for m in project.modules
                 for c, cn in m.index.calls}
        direct: Dict[str, Set[str]] = {}
        for caller, calls in wp.calls_of.items():
            leaves = {_leaf_collective(cn_of.get(id(c), ""))
                      for c in calls}
            leaves.discard(None)
            if leaves:
                direct[caller] = leaves  # type: ignore[assignment]
        cache: Dict[str, Set[str]] = {}
        for fi in wp.all_scopes():
            flagged: List[Tuple[ast.Call, str, str]] = []
            self._scan(wp, direct, cache, fi, fi.body,
                       self._tainted_locals(fi), None, flagged)
            seen: Set[int] = set()
            for call, what, why in flagged:
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield Finding(
                    self.name, fi.module.rel, call.lineno,
                    f"{what} runs only {why}: ranks that skip this "
                    f"path never reach the matching collective and "
                    f"the world hangs — make every rank execute it, "
                    f"or suppress with a rationale if the excluded "
                    f"ranks are provably parked elsewhere")


# -- 18. lock-order-cycle (whole-program) ------------------------------

class LockOrderCycle(Rule):
    """The static lock-acquisition graph over every lock-holding module
    (telemetry, flightrec, goodput, tracing, fleet, serving, faults,
    checkpoint, data/pipeline, costs): an edge A -> B when lock B is
    acquired (``with``/``acquire()``) while A is provably held — in the
    same function or through any resolved call chain.  Findings:

      * a CYCLE in the graph (two threads taking the locks in opposite
        orders deadlock);
      * a non-reentrant lock re-acquirable while already held on the
        same chain (self-deadlock through a call);
      * a SIGNAL HANDLER that can transitively acquire a non-reentrant
        ``threading.Lock`` / ``Condition(Lock())`` — the PR 12 bug
        class: the handler interrupts the very thread that may already
        hold the lock, and the process deadlocks on itself.  Handler-
        reachable locks must be RLock (or the handler lock-free)."""

    name = "lock-order-cycle"
    description = ("lock-acquisition cycles, held-lock re-acquisition, "
                   "and signal handlers that can take a non-reentrant "
                   "lock")

    def _acquire_stmt(self, wp: WholeProgram, fi: FuncInfo,
                      stmt: ast.stmt) -> Optional[str]:
        value = stmt.value if isinstance(stmt, (ast.Expr, ast.Assign)) \
            else None
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr == "acquire":
            return wp.resolve_lock(fi.modname, fi.cls, fi.env,
                                   value.func.value)
        return None

    def _scan_stmts(self, wp, fi, stmts: List[ast.stmt],
                    held: Tuple[str, ...], events: List,
                    direct: Dict[str, Set[str]],
                    sites: Dict[str, Tuple[Module, int]]) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                # runs later, not under the current holds
                self._scan_stmts(wp, fi, stmt.body, (), events,
                                 direct, sites)
                continue
            if isinstance(stmt, ast.ClassDef):
                continue  # methods have their own FuncInfo
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                cur = held
                for item in stmt.items:
                    lid = wp.resolve_lock(fi.modname, fi.cls, fi.env,
                                          item.context_expr)
                    if lid is not None:
                        self._acquire(fi, cur, lid, stmt.lineno,
                                      events, direct, sites)
                        cur = cur + (lid,)
                self._scan_stmts(wp, fi, stmt.body, cur, events,
                                 direct, sites)
                continue
            lid = self._acquire_stmt(wp, fi, stmt)
            if lid is not None:
                self._acquire(fi, held, lid, stmt.lineno, events,
                              direct, sites)
                # held until function end (release() not modeled)
                self._scan_stmts(wp, fi, stmts[i + 1:],
                                 held + (lid,), events, direct, sites)
                return
            for sub in self._sub_bodies(stmt):
                self._scan_stmts(wp, fi, sub, held, events, direct,
                                 sites)
            if held:
                for call in walk_calls(stmt):
                    q = wp.resolved.get(id(call))
                    if q is not None:
                        events.append((fi, held, "call", q,
                                       call.lineno))

    @staticmethod
    def _sub_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return [stmt.body, stmt.orelse]
        if isinstance(stmt, ast.If):
            return [stmt.body, stmt.orelse]
        if isinstance(stmt, ast.Try):
            return [stmt.body, stmt.orelse, stmt.finalbody] \
                + [h.body for h in stmt.handlers]
        return []

    def _acquire(self, fi, held, lid, lineno, events, direct,
                 sites) -> None:
        direct.setdefault(fi.qname, set()).add(lid)
        sites.setdefault(lid, (fi.module, lineno))
        events.append((fi, held, "lock", lid, lineno))

    @staticmethod
    def _lock_disp(lid: str) -> str:
        return display(lid)

    def check(self, project: Project) -> Iterator[Finding]:
        wp = project.whole_program()
        if not wp.locks:
            return
        events: List = []
        direct: Dict[str, Set[str]] = {}
        acq_sites: Dict[str, Tuple[Module, int]] = {}
        for fi in wp.all_scopes():
            self._scan_stmts(wp, fi, fi.body, (), events, direct,
                             acq_sites)

        def closure(qname: str) -> Set[str]:
            got = set(direct.get(qname, ()))
            for callee in wp.transitive_callees(qname):
                got |= direct.get(callee, set())
            return got

        # edges: (A, B) -> (fi, lineno, via) at the first site seen
        edges: Dict[Tuple[str, str], Tuple] = {}
        for fi, held, kind, target, lineno in events:
            if kind == "lock":
                acquired = {target}
                via = None
            else:
                acquired = closure(target)
                via = target
            for b in acquired:
                for a in held:
                    edges.setdefault((a, b), (fi, lineno, via))

        # re-acquisition of a held non-reentrant lock (self-deadlock)
        for (a, b), (fi, lineno, via) in sorted(edges.items()):
            if a == b and wp.non_reentrant(a):
                how = (f"through {display(via)}" if via is not None
                       else "directly")
                yield Finding(
                    self.name, fi.module.rel, lineno,
                    f"non-reentrant {wp.locks[a]} "
                    f"{self._lock_disp(a)} can be re-acquired {how} "
                    f"while already held: the second acquire blocks "
                    f"forever on the first — use threading.RLock() "
                    f"or restructure so the lock is taken once")

        # cycles (A -> B -> ... -> A), canonicalized by smallest start
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)

        def cycles_from(start: str, path: List[str],
                        found: List[List[str]]) -> None:
            for nxt in sorted(graph.get(path[-1], ())):
                if nxt == start:
                    found.append(path[:])
                elif nxt > start and nxt not in path and len(path) < 6:
                    cycles_from(start, path + [nxt], found)

        for start in sorted(graph):
            found: List[List[str]] = []
            cycles_from(start, [start], found)
            for cyc in found:
                fi, lineno, via = edges[(cyc[0], cyc[1 % len(cyc)])]
                chain = " -> ".join(self._lock_disp(c)
                                    for c in cyc + [cyc[0]])
                yield Finding(
                    self.name, fi.module.rel, lineno,
                    f"lock-order cycle {chain}: two threads taking "
                    f"these locks in opposite orders deadlock — pick "
                    f"one global order (or collapse to one lock)")

        # signal handlers reaching non-reentrant locks (PR 12 class)
        for hq, hmod, hline in wp.handlers:
            reach = closure(hq)
            for lid in sorted(reach):
                if not wp.non_reentrant(lid):
                    continue
                owners = {q for q, s in direct.items() if lid in s}
                path = wp.call_path(hq, owners)
                via = " -> ".join(display(q) for q in path) \
                    if path else display(hq)
                lmod, lline = wp.lock_sites.get(lid, (hmod, hline))
                yield Finding(
                    self.name, hmod.rel, hline,
                    f"signal handler {display(hq)} can acquire "
                    f"non-reentrant {wp.locks[lid]} "
                    f"{self._lock_disp(lid)} ({lmod.rel}:{lline}) "
                    f"via {via}: if the signal lands while this "
                    f"thread already holds it, the process "
                    f"self-deadlocks — make it an RLock or keep the "
                    f"handler lock-free")


# -- 19. mesh-axis-propagation (whole-program) -------------------------

class MeshAxisPropagation(Rule):
    """Rule 3 resolves collective axis names INSIDE one file (literals,
    ``*_AXIS`` constants, same-function defaults).  This rule follows
    the remaining case across files: a collective whose axis name is a
    function PARAMETER, resolved at every interprocedural call site —
    ``engine.step(axis_name="dtaa")`` three files away from the
    ``lax.psum(x, axis_name)`` it misconfigures.  The mechanical form
    of the ShardingPlan refactor's axis-flow audit (ROADMAP)."""

    name = "mesh-axis-propagation"
    description = ("axis-name argument flowing through call chains "
                   "into a collective must match a declared mesh axis")

    _MAX_DEPTH = 3

    def _actual_arg(self, wp: WholeProgram, fi: FuncInfo, param: str,
                    call: ast.Call) -> Optional[ast.expr]:
        got = kwarg(call, param)
        if got is not None:
            return got
        if param not in fi.params:
            return None
        idx = fi.params.index(param)
        if fi.cls is not None and not wp.call_bound.get(id(call),
                                                        True):
            idx += 1  # unbound Cls.meth(obj, ...) fills self first
        return call.args[idx] if idx < len(call.args) else None

    def _flows(self, wp: WholeProgram, fi: FuncInfo, param: str,
               consts: Dict[str, str], depth: int
               ) -> Iterator[Tuple[str, Module, int, str]]:
        """(axis value, site module, site line, chain) for every call
        site that pins this parameter to a concrete axis name."""
        if depth > self._MAX_DEPTH:
            return
        for caller_q, call, cmod in wp.call_sites.get(fi.qname, ()):
            actual = self._actual_arg(wp, fi, param, call)
            if actual is None:
                continue  # default applies: rule 3's intra-file case
            if isinstance(actual, ast.Constant) \
                    and isinstance(actual.value, str):
                yield (actual.value, cmod, call.lineno,
                       f"{display(caller_q)} -> {fi.display}")
            elif isinstance(actual, (ast.Name, ast.Attribute)) \
                    and last_seg(dotted(actual)) in consts:
                yield (consts[last_seg(dotted(actual))], cmod,
                       call.lineno,
                       f"{display(caller_q)} -> {fi.display}")
            elif isinstance(actual, ast.Name):
                cfi = wp.functions.get(caller_q)
                if cfi is not None and actual.id in cfi.kwparams:
                    for axis, smod, sline, chain in self._flows(
                            wp, cfi, actual.id, consts, depth + 1):
                        yield (axis, smod, sline,
                               f"{chain} -> {fi.display}")

    def check(self, project: Project) -> Iterator[Finding]:
        wp = project.whole_program()
        declared = declared_axes(project)
        consts = axis_constants(project)
        for mod in project.modules:
            for call, cn in mod.index.calls:
                seg = last_seg(cn)
                if seg not in _COLLECTIVES or "lax" not in cn:
                    continue
                pos = _COLLECTIVES[seg]
                axis_arg = kwarg(call, "axis_name")
                if axis_arg is None and len(call.args) > pos:
                    axis_arg = call.args[pos]
                if not isinstance(axis_arg, ast.Name):
                    continue
                fi = wp.functions.get(wp.call_caller.get(id(call), ""))
                if fi is None or axis_arg.id not in fi.kwparams:
                    continue
                for axis, smod, sline, chain in self._flows(
                        wp, fi, axis_arg.id, consts, 0):
                    if axis in declared:
                        continue
                    yield Finding(
                        self.name, smod.rel, sline,
                        f"axis {axis!r} flows through {chain} into "
                        f"{cn}() at {mod.rel}:{call.lineno}, but no "
                        f"mesh constructor declares it (declared: "
                        f"{sorted(declared)}) — the collective "
                        f"unbinds at runtime only for configs that "
                        f"reach this call chain")


# -- 20. outbound-call-without-timeout --------------------------------

class OutboundCallWithoutTimeout(Rule):
    """Control-plane code (the fleet collector, the front door, the
    rollout/autoscale loops) lives or dies by bounded outbound calls: a
    single hung replica socket with no timeout freezes the whole
    control loop — probes stop, admission stops shedding, the
    autoscaler stops repairing, and the one stuck upstream takes the
    fleet's brain down with it (ISSUE 19 satellite; deadline.py is the
    repo's sanctioned wrapper).  In serving/fleet/controller modules,
    three stdlib escape hatches are findings when no timeout reaches
    them:

      * ``urllib.request.urlopen(url)`` without ``timeout=`` — the
        stdlib default is the GLOBAL socket default, i.e. block forever;
      * ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)``
        without a timeout (kwarg or 3rd positional);
      * ``socket.create_connection(addr)`` without a timeout (kwarg or
        2nd positional).

    A ``timeout`` that is present but a literal ``None`` still counts —
    that is the block-forever spelling.  Deliberate exceptions carry a
    rationale comment on the line or the line above (same contract as
    wall-clock-in-measurement)."""

    name = "outbound-call-without-timeout"
    description = ("urlopen()/HTTPConnection()/create_connection() "
                   "without a timeout in serving/fleet/controller "
                   "code — one hung upstream must never freeze the "
                   "control loop; bound every outbound call (see "
                   "deadline.py)")
    TARGET_BASENAMES = {"fleet.py", "deadline.py", "frontdoor.py",
                        "controller.py", "rollout.py"}

    _has_rationale = BlockingH2dInStepLoop._has_rationale

    def _targets(self, mod: Module) -> bool:
        return (mod.basename in self.TARGET_BASENAMES
                or "serving" in mod.rel.replace("\\", "/").split("/")[:-1])

    @staticmethod
    def _timeout_arg(call: ast.Call, pos: int) -> Optional[ast.AST]:
        arg = kwarg(call, "timeout")
        if arg is None and len(call.args) > pos:
            arg = call.args[pos]
        return arg

    def _unbounded(self, call: ast.Call) -> Optional[str]:
        """The offending callable's name, or None when the call either
        is not an outbound ctor or carries a real timeout."""
        cn = call_name(call)
        last, root = last_seg(cn), root_seg(cn)
        if root == last:
            root = ""  # bare from-import: urlopen(...), HTTPConnection(...)
        if last == "urlopen" and root in ("urllib", "request", "", "dl"):
            pos = 99  # urlopen's timeout is keyword-position 3; treat
            # positional use as absent — nobody threads data/cafile
        elif last in ("HTTPConnection", "HTTPSConnection") \
                and root in ("http", "client", ""):
            pos = 2  # HTTPConnection(host, port, timeout)
        elif last == "create_connection" and root in ("socket", ""):
            pos = 1  # create_connection(address, timeout)
        else:
            return None
        arg = self._timeout_arg(call, pos)
        if arg is None or (isinstance(arg, ast.Constant)
                           and arg.value is None):
            return cn
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not self._targets(mod):
                continue
            for node in mod.index.nodes:
                if not isinstance(node, ast.Call):
                    continue
                cn = self._unbounded(node)
                if cn is None:
                    continue
                if self._has_rationale(mod, node.lineno):
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"{cn}() without a timeout in control-plane code: "
                    f"the stdlib default blocks forever, so one hung "
                    f"upstream freezes probes, shedding and "
                    f"autoscaling fleet-wide — pass timeout= (or use "
                    f"deadline.fetch/post_json), or comment why this "
                    f"call is bounded elsewhere")


class NondeterminismInPolicy(Rule):
    """The fleet simulator (sim/) replays the REAL control-plane
    policies under a virtual clock, and same-seed runs must produce
    byte-identical event logs — which only holds while the deciders
    stay pure functions of (config, sample window).  One ``time.time()``
    or unseeded RNG draw inside a decider silently forks the simulated
    fleet from the live one AND breaks replay determinism, the two
    properties the ISSUE-20 gate rests on.  In the pure decider modules
    (slo.py, serving/{planner,controller,rollout}.py and everything
    under sim/), findings are:

      * importing ``time`` or ``datetime`` at all — a pure decider has
        no business holding a clock; samples carry their own ``t``;
      * wall/monotonic clock calls (``time.*``, ``datetime.now`` /
        ``utcnow`` / ``today``);
      * ambient entropy: ``os.urandom``, ``uuid.uuid4``, ``secrets.*``,
        module-global ``random.<draw>()``, and zero-arg
        ``random.Random()`` (seeded from the OS clock).

    ``random.Random(seed)`` WITH an argument is allowed — a seeded
    stream is part of the deterministic replay, not entropy.  In
    serving/frontdoor.py (a live process with legitimate clocks in its
    serving loop) only the pure decision helpers the simulator composes
    are held to this: decide_health / routable_ids / pick_upstream /
    admission.  Deliberate exceptions carry a rationale comment on the
    line or the line above."""

    name = "nondeterminism-in-policy"
    description = ("wall clock / ambient entropy inside a pure decider "
                   "module (slo, planner, controller, rollout, sim/) — "
                   "policies must stay pure functions of (config, "
                   "samples) or the fleet simulator's byte-identical "
                   "replay contract breaks")

    TARGET_BASENAMES = {"slo.py", "planner.py", "controller.py",
                        "rollout.py"}
    FRONTDOOR_FUNCS = {"decide_health", "routable_ids", "pick_upstream",
                       "admission"}
    _CLOCK_IMPORTS = {"time", "datetime"}
    _DT_CALLS = {"now", "utcnow", "today"}

    _has_rationale = BlockingH2dInStepLoop._has_rationale

    def _whole_module(self, mod: Module) -> bool:
        rel = mod.rel.replace("\\", "/").split("/")
        return mod.basename in self.TARGET_BASENAMES or "sim" in rel[:-1]

    def _frontdoor_lines(self, mod: Module) -> List[Tuple[int, int]]:
        return [(fn.lineno, fn.end_lineno or fn.lineno)
                for fn in mod.index.functions
                if getattr(fn, "name", "") in self.FRONTDOOR_FUNCS]

    def _bad_call(self, call: ast.Call) -> Optional[str]:
        """The impure callable's dotted name, or None."""
        cn = call_name(call)
        last, root = last_seg(cn), root_seg(cn)
        if root == "time":
            return cn
        if root in ("datetime", "dt") and last in self._DT_CALLS:
            return cn
        if cn == "os.urandom" or cn == "uuid.uuid4" or root == "secrets":
            return cn
        if root == "random":
            if last == "Random":
                # Seeded stream = deterministic; zero-arg = OS entropy.
                return None if (call.args or call.keywords) else cn
            return cn
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            whole = self._whole_module(mod)
            spans = [] if whole else (
                self._frontdoor_lines(mod)
                if mod.basename == "frontdoor.py" else None)
            if not whole and spans is None:
                continue

            def targeted(line: int) -> bool:
                return whole or any(lo <= line <= hi
                                    for lo, hi in spans)

            for node in mod.index.nodes:
                if whole and isinstance(node, (ast.Import,
                                               ast.ImportFrom)):
                    names = ([a.name for a in node.names]
                             if isinstance(node, ast.Import)
                             else [node.module or ""])
                    hit = [n for n in names
                           if n.split(".")[0] in self._CLOCK_IMPORTS]
                    if hit and not self._has_rationale(mod, node.lineno):
                        yield self.finding(
                            mod, node.lineno,
                            f"import of {hit[0]!r} in a pure decider "
                            f"module: deciders take time from their "
                            f"samples (each carries its own 't'), "
                            f"never from a clock — the fleet "
                            f"simulator's byte-identical replay "
                            f"depends on it")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                cn = self._bad_call(node)
                if cn is None or not targeted(node.lineno):
                    continue
                if self._has_rationale(mod, node.lineno):
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"{cn}() inside pure policy code: this decider "
                    f"runs under the fleet simulator's virtual clock, "
                    f"where wall time and ambient entropy silently "
                    f"fork the replay — take t from the sample window, "
                    f"or thread a seeded random.Random through the "
                    f"config")


RULES = (
    HostSyncInStepLoop(),
    TraceImpurity(),
    CollectiveAxisConsistency(),
    PrngReuse(),
    MissingDonation(),
    ThreadSharedState(),
    ConfigDrift(),
    BareExcept(),
    RetryWithoutBackoff(),
    ProfilerTraceLeak(),
    MixedPrecisionAccum(),
    CollectiveInCleanup(),
    WallClockInMeasurement(),
    BlockingH2dInStepLoop(),
    UnboundedQueueInServer(),
    UnboundedMetricCardinality(),
    CollectiveDivergence(),
    LockOrderCycle(),
    MeshAxisPropagation(),
    OutboundCallWithoutTimeout(),
    NondeterminismInPolicy(),
)

RULES_BY_NAME = {r.name: r for r in RULES}
