"""distributedpytorch_tpu — a TPU-native (JAX/XLA) re-design of
georand/distributedpytorch.

The reference (`/root/reference`, 962 lines of Python) is a multi-node,
multi-GPU Distributed Data Parallel image-classification trainer built on
torch.distributed/NCCL.  This package provides the same capability set —
SPMD launch, collective-backed data-parallel training, sharded data loading,
checkpoint/resume, a train/test CLI, a model zoo, a loss zoo, seeding and
logging — re-architected idiomatically for TPU:

  * topology comes from the JAX runtime (``jax.distributed.initialize`` +
    ``jax.process_index``), not a hand-edited IP table
    (ref: main.py:60-110);
  * the DDP wrapper's hidden gradient allreduce (ref: classif.py:138)
    becomes a compiler-inserted all-reduce: the train step is jit-compiled
    over batches sharded along the mesh's 'data' axis, and XLA places the
    gradient reduction exactly where DDP's hidden one was;
  * ``DistributedSampler`` (ref: dataloader.py:147-152) becomes a
    deterministic, epoch-keyed global permutation sharded by process index;
  * data augmentation runs *on device* as a single fused affine warp inside
    jit — there is no host-side transform pipeline to bottleneck on.

Layer map (mirrors SURVEY.md §1):

  L0  config          distributedpytorch_tpu.config
  L1  runtime/utils   distributedpytorch_tpu.runtime, .utils, .checkpoint
  L2  data            distributedpytorch_tpu.data
  L3  engine          distributedpytorch_tpu.train, .ops
  L4  launcher/CLI    distributedpytorch_tpu.cli  (entry: main.py)
  --  models          distributedpytorch_tpu.models
  --  parallelism     distributedpytorch_tpu.parallel  (model-axis param/
                      optimizer sharding over the 2-D mesh; data
                      parallelism itself lives in the engine + runtime;
                      sequence parallelism = ops.attention ring attention)

Framework additions beyond the reference's capability set (each tested):
ViT model family + sequence-parallel ring attention, gradient
accumulation, model-parallel (ZeRO-style) param sharding, preemption-safe
graceful shutdown with cross-host agreement, analytic FLOP/MFU accounting.
"""

__version__ = "0.1.0"
